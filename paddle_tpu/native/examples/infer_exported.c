/* StableHLO-artifact C deployment example: serve a model exported with
 * paddle_tpu.export.export_inference from a C service — no config file,
 * no merged params, one self-contained compiler-level artifact (the
 * merge_model -> C-API story of the reference, carried to the XLA era).
 *
 * Build:
 *   gcc infer_exported.c -I../include -L.. -lpaddle_tpu_capi \
 *       -Wl,-rpath,.. -o infer_exported
 * Run:
 *   ./infer_exported <repo_root> <model.shlo>
 */
#include <stdio.h>
#include <stdlib.h>

#include "paddle_tpu_capi.h"

int main(int argc, char** argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: %s <repo_root> <model.shlo>\n", argv[0]);
    return 2;
  }
  if (pt_capi_init(argv[1]) != 0) {
    fprintf(stderr, "init failed: %s\n", pt_capi_last_error());
    return 1;
  }
  int64_t m = pt_capi_create_exported(argv[2]);
  if (m < 0) {
    fprintf(stderr, "create_exported failed: %s\n", pt_capi_last_error());
    return 1;
  }

  /* the artifact in the test is exported with feed_spec x:[2,4] */
  float input[2 * 4] = {1.f, 0.f, 0.f, 0.f,
                        0.f, 0.f, 0.f, 1.f};
  if (pt_capi_set_input_dense(m, "x", input, 2, 4) != 0 ||
      pt_capi_run(m) < 1) {
    fprintf(stderr, "forward failed: %s\n", pt_capi_last_error());
    return 1;
  }
  int64_t rows = 0, cols = 0;
  pt_capi_output_shape(m, 0, &rows, &cols);
  float* out = (float*)malloc(sizeof(float) * rows * cols);
  pt_capi_get_output(m, 0, out, rows * cols);
  for (int64_t i = 0; i < rows; ++i) {
    printf("row %lld:", (long long)i);
    for (int64_t j = 0; j < cols; ++j) printf(" %.4f", out[i * cols + j]);
    printf("\n");
  }
  free(out);
  pt_capi_destroy(m);
  return 0;
}
