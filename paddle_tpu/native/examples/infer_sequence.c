/* Sequence-input C deployment example (reference capi/examples/
 * model_inference/sequence/main.c: integer-id sentence + explicit
 * sequence start positions).  The TPU-native API feeds a padded id batch
 * with per-row lengths instead of start positions — same information,
 * static shapes for XLA.
 *
 * Build:
 *   gcc infer_sequence.c -I../include -L.. -lpaddle_tpu_capi \
 *       -Wl,-rpath,.. -o infer_sequence
 * Run:
 *   ./infer_sequence <repo_root> <config.py> <model.npz>
 */
#include <stdio.h>
#include <stdlib.h>

#include "paddle_tpu_capi.h"

int main(int argc, char** argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <repo_root> <config.py> <model.npz>\n",
            argv[0]);
    return 2;
  }
  if (pt_capi_init(argv[1]) != 0) {
    fprintf(stderr, "init failed: %s\n", pt_capi_last_error());
    return 1;
  }
  int64_t m = pt_capi_create(argv[2], argv[3]);
  if (m < 0) {
    fprintf(stderr, "create failed: %s\n", pt_capi_last_error());
    return 1;
  }

  /* Two sentences of different length, padded to max_len = 6; the
   * per-row lengths mark the real tokens (reference: sequence_start_pos
   * {0, 9} over a flat id vector). */
  enum { ROWS = 2, MAX_LEN = 6 };
  int32_t ids[ROWS * MAX_LEN] = {
      7, 3, 1, 4, 2, 5,  /* full-length sentence            */
      9, 8, 6, 0, 0, 0}; /* 3 real tokens + 3 padding slots */
  int32_t lengths[ROWS] = {6, 3};

  if (pt_capi_set_input_ids(m, "ids", ids, ROWS, MAX_LEN, lengths) != 0 ||
      pt_capi_run(m) < 1) {
    fprintf(stderr, "forward failed: %s\n", pt_capi_last_error());
    return 1;
  }
  int64_t rows = 0, cols = 0;
  pt_capi_output_shape(m, 0, &rows, &cols);
  float* out = (float*)malloc(sizeof(float) * rows * cols);
  pt_capi_get_output(m, 0, out, rows * cols);
  for (int64_t i = 0; i < rows; ++i) {
    printf("row %lld:", (long long)i);
    for (int64_t j = 0; j < cols; ++j) printf(" %.4f", out[i * cols + j]);
    printf("\n");
  }
  free(out);
  pt_capi_destroy(m);
  return 0;
}
