/* Sparse-binary-input C deployment example (reference capi/examples/
 * model_inference/sparse_binary/main.c: CSR row offsets + column ids via
 * paddle_matrix_create_sparse / paddle_matrix_sparse_copy_from).
 *
 * Build:
 *   gcc infer_sparse_binary.c -I../include -L.. -lpaddle_tpu_capi \
 *       -Wl,-rpath,.. -o infer_sparse_binary
 * Run:
 *   ./infer_sparse_binary <repo_root> <config.py> <model.npz>
 */
#include <stdio.h>
#include <stdlib.h>

#include "paddle_tpu_capi.h"

int main(int argc, char** argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <repo_root> <config.py> <model.npz>\n",
            argv[0]);
    return 2;
  }
  if (pt_capi_init(argv[1]) != 0) {
    fprintf(stderr, "init failed: %s\n", pt_capi_last_error());
    return 1;
  }
  int64_t m = pt_capi_create(argv[2], argv[3]);
  if (m < 0) {
    fprintf(stderr, "create failed: %s\n", pt_capi_last_error());
    return 1;
  }

  /* Two rows over a 64-wide sparse-binary feature space: row 0 sets
   * columns {9, 13, 47}, row 1 sets {2, 60} (reference colBuf/rowBuf). */
  enum { DIM = 64 };
  int32_t col_ids[] = {9, 13, 47, 2, 60};
  int32_t row_offsets[] = {0, 3, 5};

  if (pt_capi_set_input_sparse_binary(m, "x", DIM, col_ids, 5, row_offsets,
                                      3) != 0 ||
      pt_capi_run(m) < 1) {
    fprintf(stderr, "forward failed: %s\n", pt_capi_last_error());
    return 1;
  }
  int64_t rows = 0, cols = 0;
  pt_capi_output_shape(m, 0, &rows, &cols);
  float* out = (float*)malloc(sizeof(float) * rows * cols);
  pt_capi_get_output(m, 0, out, rows * cols);
  for (int64_t i = 0; i < rows; ++i) {
    printf("row %lld:", (long long)i);
    for (int64_t j = 0; j < cols; ++j) printf(" %.4f", out[i * cols + j]);
    printf("\n");
  }
  free(out);
  pt_capi_destroy(m);
  return 0;
}
