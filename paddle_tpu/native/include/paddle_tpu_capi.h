/* C inference API for paddle_tpu (reference: paddle/capi/
 * gradient_machine.h, matrix.h, arguments.h, error.h — collapsed to a
 * handle-based create/set/run/get surface; the compute runs on the default
 * JAX/XLA device behind an embedded CPython).
 *
 * Usage (see native/examples/infer_dense.c):
 *   pt_capi_init("/path/to/repo");            // adds repo to sys.path
 *   int64_t m = pt_capi_create("config.py", "model.npz");
 *   pt_capi_set_input_dense(m, "img", data, rows, cols);
 *   int n_out = pt_capi_run(m);
 *   int64_t r, c; pt_capi_output_shape(m, 0, &r, &c);
 *   pt_capi_get_output(m, 0, buf, r * c);
 *   pt_capi_destroy(m);
 */
#ifndef PADDLE_TPU_CAPI_H
#define PADDLE_TPU_CAPI_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Initialize the embedded interpreter; extra_sys_path (may be NULL) is
 * prepended to sys.path.  Returns 0 on success. */
int pt_capi_init(const char* extra_sys_path);

/* Human-readable description of the last failure. */
const char* pt_capi_last_error(void);

/* Build an inference machine from a Python config file (defines `predict`
 * or `__outputs__`) and a merged model file (trainer.checkpoint.
 * merge_model).  Returns a handle > 0, or -1. */
int64_t pt_capi_create(const char* config_path, const char* params_path);

/* Build an inference machine from a serialized StableHLO artifact
 * (paddle_tpu.export.export_inference) — self-contained, no config or
 * params file.  Returns a handle > 0, or -1. */
int64_t pt_capi_create_exported(const char* artifact_path);

/* Set a dense float32 input [rows, cols] for data layer `name`. */
int pt_capi_set_input_dense(int64_t h, const char* name, const float* data,
                            int64_t rows, int64_t cols);

/* Set integer ids: cols == 0 -> plain [rows] ids; cols > 0 -> padded
 * sequence batch [rows, cols] with per-row lengths (lengths may be NULL
 * for full-length rows). */
int pt_capi_set_input_ids(int64_t h, const char* name, const int32_t* ids,
                          int64_t rows, int64_t cols,
                          const int32_t* lengths);

/* Sparse-binary input in CSR form: row_offsets has rows+1 entries and
 * col_ids[row_offsets[i]..row_offsets[i+1]) are the set columns of row i
 * (reference paddle_matrix_create_sparse + sparse_copy_from).  Densified
 * to float32 [rows, dim] before feeding. */
int pt_capi_set_input_sparse_binary(int64_t h, const char* name, int64_t dim,
                                    const int32_t* col_ids, int64_t n_cols,
                                    const int32_t* row_offsets,
                                    int64_t n_offsets);

/* New handle sharing h's loaded parameters (reference
 * paddle_gradient_machine_create_shared_param); every thread should run
 * on its own clone so inputs/outputs don't race.  Returns handle > 0 or
 * -1. */
int64_t pt_capi_clone(int64_t h);

/* Run forward.  Returns the number of outputs, or -1. */
int pt_capi_run(int64_t h);

/* Output idx shape as [rows, cols] (trailing dims flattened into cols). */
int pt_capi_output_shape(int64_t h, int idx, int64_t* rows, int64_t* cols);

/* Copy output idx (float32) into buf; capacity in floats.  Returns the
 * number of floats written, or -1. */
int pt_capi_get_output(int64_t h, int idx, float* buf, int64_t capacity);

int pt_capi_destroy(int64_t h);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_CAPI_H */
