"""ctypes bindings for the native data-path runtime (src/dataio.cpp).

Build: python -m paddle_tpu.native.build   (g++ -O3 -shared; no deps).
Falls back gracefully — is_available() gates the fast paths; the pure-Python
feeder keeps working without the .so.
"""

import ctypes
import os

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libpaddle_tpu_dataio.so")
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    # binaries are not committed; build on first use and rebuild when the
    # source is newer than the binary (best-effort — ensure() no-ops fast
    # when the .so is current)
    from paddle_tpu.native import build as _build
    _build.ensure("dataio")
    if not os.path.exists(_SO):
        return None
    lib = ctypes.CDLL(_SO)
    lib.pt_pack_i32.restype = ctypes.c_int
    lib.pt_pack_f32.restype = ctypes.c_int
    lib.pt_densify_sparse.restype = ctypes.c_int
    lib.pt_writer_open.restype = ctypes.c_void_p
    lib.pt_writer_open.argtypes = [ctypes.c_char_p]
    lib.pt_writer_put.restype = ctypes.c_int
    lib.pt_writer_put.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint8),
                                  ctypes.c_uint32]
    lib.pt_writer_close.restype = ctypes.c_int
    lib.pt_writer_close.argtypes = [ctypes.c_void_p]
    lib.pt_reader_open.restype = ctypes.c_void_p
    lib.pt_reader_open.argtypes = [ctypes.c_char_p]
    lib.pt_reader_next.restype = ctypes.c_int64
    lib.pt_reader_next.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    lib.pt_reader_close.restype = ctypes.c_int
    lib.pt_reader_close.argtypes = [ctypes.c_void_p]
    lib.pt_queue_create.restype = ctypes.c_void_p
    lib.pt_queue_create.argtypes = [ctypes.c_int32]
    lib.pt_queue_add_file.restype = ctypes.c_int
    lib.pt_queue_add_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.pt_queue_pop.restype = ctypes.c_int64
    lib.pt_queue_pop.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                                 ctypes.c_int32]
    lib.pt_queue_destroy.restype = ctypes.c_int
    lib.pt_queue_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def is_available():
    return _load() is not None


def pack_i32(seqs, max_len=None, pad=0):
    """seqs: list of 1-D int32 arrays -> (out [B, T] int32, lengths [B])."""
    lib = _load()
    b = len(seqs)
    arrs = [np.ascontiguousarray(s, dtype=np.int32) for s in seqs]
    lens = np.asarray([len(a) for a in arrs], np.int32)
    t = int(max_len or (lens.max() if b else 1))
    out = np.empty((b, t), np.int32)
    out_lens = np.empty((b,), np.int32)
    ptrs = (ctypes.POINTER(ctypes.c_int32) * b)(
        *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)) for a in arrs])
    rc = lib.pt_pack_i32(ptrs, lens.ctypes.data_as(
        ctypes.POINTER(ctypes.c_int32)), b, t, pad,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        raise RuntimeError(f"pt_pack_i32 failed rc={rc}")
    return out, out_lens


def pack_f32(seqs, max_len=None):
    """seqs: list of [len, dim] float32 arrays -> ([B, T, D], lengths)."""
    lib = _load()
    b = len(seqs)
    arrs = [np.ascontiguousarray(s, dtype=np.float32) for s in seqs]
    dim = arrs[0].shape[1]
    lens = np.asarray([a.shape[0] for a in arrs], np.int32)
    t = int(max_len or (lens.max() if b else 1))
    out = np.empty((b, t, dim), np.float32)
    out_lens = np.empty((b,), np.int32)
    ptrs = (ctypes.POINTER(ctypes.c_float) * b)(
        *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for a in arrs])
    rc = lib.pt_pack_f32(ptrs, lens.ctypes.data_as(
        ctypes.POINTER(ctypes.c_int32)), b, t, dim,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        raise RuntimeError(f"pt_pack_f32 failed rc={rc}")
    return out, out_lens


def densify_sparse(rows, cols, vals, b, dim):
    lib = _load()
    rows = np.ascontiguousarray(rows, np.int32)
    cols = np.ascontiguousarray(cols, np.int32)
    out = np.empty((b, dim), np.float32)
    vp = None
    if vals is not None:
        vals = np.ascontiguousarray(vals, np.float32)
        vp = vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    rc = lib.pt_densify_sparse(
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vp, len(rows), b, dim,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    if rc != 0:
        raise RuntimeError(f"pt_densify_sparse failed rc={rc}")
    return out


class RecordWriter:
    """PTRC record-file writer (the ProtoDataProvider binary-format role)."""

    def __init__(self, path):
        lib = _load()
        self._lib = lib
        self._h = lib.pt_writer_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def put(self, payload: bytes):
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        rc = self._lib.pt_writer_put(self._h, buf, len(payload))
        if rc != 0:
            raise IOError(f"write failed rc={rc}")

    def close(self):
        if self._h:
            self._lib.pt_writer_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class RecordReader:
    def __init__(self, path):
        lib = _load()
        self._lib = lib
        self._h = lib.pt_reader_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def __iter__(self):
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        while True:
            n = self._lib.pt_reader_next(self._h, ctypes.byref(ptr))
            if n < 0:
                if n == -2:
                    raise IOError("corrupt record file")
                break
            yield ctypes.string_at(ptr, n)

    def close(self):
        if self._h:
            self._lib.pt_reader_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class PrefetchQueue:
    """Native worker threads stream record files into a bounded queue
    (the DoubleBuffer async-load role)."""

    def __init__(self, capacity=64):
        self._lib = _load()
        self._h = self._lib.pt_queue_create(capacity)

    def add_file(self, path):
        rc = self._lib.pt_queue_add_file(self._h, path.encode())
        if rc != 0:
            raise IOError(f"add_file failed rc={rc}")

    def pop(self, timeout_ms=1000):
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.pt_queue_pop(self._h, ctypes.byref(ptr), timeout_ms)
        if n < 0:
            return None
        return ctypes.string_at(ptr, n)

    def close(self):
        if self._h:
            self._lib.pt_queue_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
