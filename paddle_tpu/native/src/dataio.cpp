// Native data-path runtime: ragged-batch packing + binary record IO +
// background prefetch pool.
//
// TPU-native counterpart of the reference's C++ data plane —
// gserver/dataproviders/{DataProvider.cpp (DoubleBuffer), ProtoDataProvider,
// PyDataProvider2.cpp} and the SequenceToBatch packing in
// gserver/layers/SequenceToBatch.cpp.  The compute side is XLA; this native
// module owns what stays on the host: turning millions of small ragged
// Python/numpy sequences into padded device-ready buffers without the
// Python interpreter in the per-token loop, and streaming record files with
// a worker pool ahead of the train step.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>
#include <atomic>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- packing

// Pack B ragged int32 sequences into out[B, max_len] (pre-allocated),
// writing lengths[B].  pad fills the tail.  Returns 0 on success.
int pt_pack_i32(const int32_t** seqs, const int32_t* lens, int32_t b,
                int32_t max_len, int32_t pad, int32_t* out,
                int32_t* out_lens) {
  if (!seqs || !lens || !out || !out_lens || b < 0 || max_len <= 0) return -1;
  for (int32_t i = 0; i < b; ++i) {
    int32_t n = lens[i] < max_len ? lens[i] : max_len;
    if (n > 0) std::memcpy(out + (size_t)i * max_len, seqs[i],
                           (size_t)n * sizeof(int32_t));
    for (int32_t t = n; t < max_len; ++t) out[(size_t)i * max_len + t] = pad;
    out_lens[i] = n;
  }
  return 0;
}

// Pack B ragged float32 sequences of row width `dim` into
// out[B, max_len, dim]; zero-fill padding.
int pt_pack_f32(const float** seqs, const int32_t* lens, int32_t b,
                int32_t max_len, int32_t dim, float* out, int32_t* out_lens) {
  if (!seqs || !lens || !out || !out_lens || b < 0 || max_len <= 0 || dim <= 0)
    return -1;
  const size_t row = (size_t)max_len * dim;
  for (int32_t i = 0; i < b; ++i) {
    int32_t n = lens[i] < max_len ? lens[i] : max_len;
    if (n > 0) std::memcpy(out + (size_t)i * row, seqs[i],
                           (size_t)n * dim * sizeof(float));
    std::memset(out + (size_t)i * row + (size_t)n * dim, 0,
                ((size_t)(max_len - n) * dim) * sizeof(float));
    out_lens[i] = n;
  }
  return 0;
}

// Scatter sparse (row, col, value) triples into a dense [b, dim] f32 matrix.
int pt_densify_sparse(const int32_t* rows, const int32_t* cols,
                      const float* vals, int64_t nnz, int32_t b, int32_t dim,
                      float* out) {
  if (!rows || !cols || !out) return -1;
  std::memset(out, 0, (size_t)b * dim * sizeof(float));
  for (int64_t k = 0; k < nnz; ++k) {
    int32_t r = rows[k], c = cols[k];
    if (r < 0 || r >= b || c < 0 || c >= dim) return -2;
    out[(size_t)r * dim + c] = vals ? vals[k] : 1.0f;
  }
  return 0;
}

// ---------------------------------------------------------------- records
//
// Record file format (the ProtoDataProvider/DataFormat.proto role, redesigned
// as a flat mmap-friendly stream):
//   magic "PTRC" | u32 version
//   per record: u32 payload_bytes | payload
// Payload layout is caller-defined (typically a packed sample).

struct PtWriter {
  FILE* f;
};

void* pt_writer_open(const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  const char magic[4] = {'P', 'T', 'R', 'C'};
  uint32_t version = 1;
  if (std::fwrite(magic, 1, 4, f) != 4 ||
      std::fwrite(&version, 4, 1, f) != 1) {
    std::fclose(f);
    return nullptr;
  }
  auto* w = new PtWriter{f};
  return w;
}

int pt_writer_put(void* handle, const uint8_t* data, uint32_t size) {
  auto* w = static_cast<PtWriter*>(handle);
  if (!w || !w->f) return -1;
  if (std::fwrite(&size, 4, 1, w->f) != 1) return -2;
  if (size && std::fwrite(data, 1, size, w->f) != size) return -2;
  return 0;
}

int pt_writer_close(void* handle) {
  auto* w = static_cast<PtWriter*>(handle);
  if (!w) return -1;
  int rc = std::fclose(w->f);
  delete w;
  return rc;
}

struct PtReader {
  FILE* f;
  std::vector<uint8_t> buf;
};

void* pt_reader_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  char magic[4];
  uint32_t version = 0;
  if (std::fread(magic, 1, 4, f) != 4 || std::memcmp(magic, "PTRC", 4) != 0 ||
      std::fread(&version, 4, 1, f) != 1 || version != 1) {
    std::fclose(f);
    return nullptr;
  }
  return new PtReader{f, {}};
}

// Returns payload size (>=0) and fills *out with an internal pointer valid
// until the next call; -1 on EOF, -2 on corruption.
int64_t pt_reader_next(void* handle, const uint8_t** out) {
  auto* r = static_cast<PtReader*>(handle);
  if (!r || !r->f) return -2;
  uint32_t size = 0;
  size_t got = std::fread(&size, 4, 1, r->f);
  if (got != 1) return -1;  // EOF
  r->buf.resize(size);
  if (size && std::fread(r->buf.data(), 1, size, r->f) != size) return -2;
  *out = r->buf.data();
  return (int64_t)size;
}

int pt_reader_close(void* handle) {
  auto* r = static_cast<PtReader*>(handle);
  if (!r) return -1;
  int rc = std::fclose(r->f);
  delete r;
  return rc;
}

// ------------------------------------------------------------ prefetch pool
//
// Bounded MPMC byte-blob queue: producer threads read record files, the
// consumer (Python) pops assembled payloads.  This is the DoubleBuffer /
// AsyncThreadPool role (utils/Thread.h:478, DataProvider.h:251) without
// touching the GIL on the producer side.

struct PtQueue {
  std::deque<std::vector<uint8_t>> q;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  size_t capacity;
  std::atomic<bool> closed{false};
  std::vector<std::thread> workers;
  std::vector<uint8_t> last;
};

void* pt_queue_create(int32_t capacity) {
  auto* pq = new PtQueue();
  pq->capacity = capacity > 0 ? (size_t)capacity : 64;
  return pq;
}

// Start a producer thread streaming every record of `path` into the queue.
int pt_queue_add_file(void* handle, const char* path) {
  auto* pq = static_cast<PtQueue*>(handle);
  if (!pq) return -1;
  std::string p(path);
  pq->workers.emplace_back([pq, p]() {
    void* r = pt_reader_open(p.c_str());
    if (!r) return;
    const uint8_t* data = nullptr;
    int64_t n;
    while ((n = pt_reader_next(r, &data)) >= 0) {
      std::vector<uint8_t> blob(data, data + n);
      std::unique_lock<std::mutex> lk(pq->mu);
      pq->cv_push.wait(lk, [pq] {
        return pq->q.size() < pq->capacity || pq->closed.load();
      });
      if (pq->closed.load()) break;
      pq->q.emplace_back(std::move(blob));
      pq->cv_pop.notify_one();
    }
    pt_reader_close(r);
  });
  return 0;
}

// Pop one payload; blocks up to timeout_ms.  Returns size, or -1 on
// timeout/closed-and-empty.  Pointer valid until next pop on this queue.
int64_t pt_queue_pop(void* handle, const uint8_t** out, int32_t timeout_ms) {
  auto* pq = static_cast<PtQueue*>(handle);
  if (!pq) return -2;
  std::unique_lock<std::mutex> lk(pq->mu);
  bool ok = pq->cv_pop.wait_for(
      lk, std::chrono::milliseconds(timeout_ms),
      [pq] { return !pq->q.empty(); });
  if (!ok) return -1;
  pq->last = std::move(pq->q.front());
  pq->q.pop_front();
  pq->cv_push.notify_one();
  *out = pq->last.data();
  return (int64_t)pq->last.size();
}

int pt_queue_destroy(void* handle) {
  auto* pq = static_cast<PtQueue*>(handle);
  if (!pq) return -1;
  pq->closed.store(true);
  pq->cv_push.notify_all();
  pq->cv_pop.notify_all();
  for (auto& t : pq->workers)
    if (t.joinable()) t.join();
  delete pq;
  return 0;
}

}  // extern "C"
