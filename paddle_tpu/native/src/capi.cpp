// C inference API: embed CPython, drive paddle_tpu.capi_backend.
//
// TPU-native counterpart of the reference's deployment C API
// (capi/gradient_machine.h:36-59 paddle_gradient_machine_create_for_
// inference/forward, capi/matrix.h, capi/error.h), combined with the
// reference's own embedded-Python precedent (utils/PythonUtil.cpp:48
// callPythonFunc).  The XLA runtime stays behind JAX; this shim gives
// C/C++ applications a stable ABI: create(config, merged_params) ->
// set inputs -> run -> read outputs.
//
// Thread-safety: every entry point takes the GIL (PyGILState_Ensure), so
// the library is safe to call from multiple native threads; compute runs
// on the default JAX device.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::mutex g_init_mu;
bool g_we_initialized = false;
// thread_local: each native thread reads its own last failure (the clone-
// based multi-thread pattern makes concurrent failures reachable, and a
// shared std::string would be a use-after-free race under c_str()).
thread_local std::string g_last_error;

PyObject* backend() {  // borrowed-style cached module ref (owned here)
  static PyObject* mod = nullptr;
  if (!mod) {
    mod = PyImport_ImportModule("paddle_tpu.capi_backend");
    if (!mod) {
      PyErr_Print();
      g_last_error = "cannot import paddle_tpu.capi_backend (is the repo "
                     "root on PYTHONPATH?)";
    }
  }
  return mod;
}

void capture_py_error() {
  PyObject* mod = backend();
  if (!mod) return;
  PyObject* fn = PyObject_GetAttrString(mod, "last_error");
  if (!fn) return;
  PyObject* s = PyObject_CallObject(fn, nullptr);
  Py_DECREF(fn);
  if (s && PyUnicode_Check(s)) g_last_error = PyUnicode_AsUTF8(s);
  Py_XDECREF(s);
}

}  // namespace

extern "C" {

// Initialize the embedded interpreter (no-op when the host process already
// runs Python, e.g. tests).  extra_sys_path: repo root, may be NULL.
int pt_capi_init(const char* extra_sys_path) {
  std::lock_guard<std::mutex> lock(g_init_mu);
  bool just_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = just_initialized = true;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 0;
  if (extra_sys_path && *extra_sys_path) {
    PyObject* sys_path = PySys_GetObject("path");  // borrowed
    PyObject* p = PyUnicode_FromString(extra_sys_path);
    if (!sys_path || !p || PyList_Insert(sys_path, 0, p) != 0) rc = -1;
    Py_XDECREF(p);
  }
  if (rc == 0 && !backend()) rc = -1;
  PyGILState_Release(gil);
  if (just_initialized) {
    // Py_InitializeEx leaves this thread owning the GIL; release it so
    // other native threads' PyGILState_Ensure can acquire it (the
    // multi-thread guarantee in the file header).
    PyEval_SaveThread();
  }
  return rc;
}

const char* pt_capi_last_error() { return g_last_error.c_str(); }

// Build a machine from a Python config file + merged params file.
// Returns handle > 0, or -1 (see pt_capi_last_error).
int64_t pt_capi_create(const char* config_path, const char* params_path) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int64_t handle = -1;
  PyObject* mod = backend();
  if (mod) {
    PyObject* r = PyObject_CallMethod(mod, "create", "ss", config_path,
                                      params_path);
    if (r && PyLong_Check(r)) handle = PyLong_AsLongLong(r);
    if (!r) PyErr_Print();
    Py_XDECREF(r);
    if (handle < 0) capture_py_error();
  }
  PyGILState_Release(gil);
  return handle;
}

// Build a machine from a serialized StableHLO artifact
// (paddle_tpu.export.export_inference output) — self-contained: no config
// file or merged params needed.  Returns handle > 0, or -1.
int64_t pt_capi_create_exported(const char* artifact_path) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int64_t handle = -1;
  PyObject* mod = backend();
  if (mod) {
    PyObject* r = PyObject_CallMethod(mod, "create_exported", "s",
                                      artifact_path);
    if (r && PyLong_Check(r)) handle = PyLong_AsLongLong(r);
    if (!r) PyErr_Print();
    Py_XDECREF(r);
    if (handle < 0) capture_py_error();
  }
  PyGILState_Release(gil);
  return handle;
}

// Dense input [rows, cols] float32 for data layer `name`.
int pt_capi_set_input_dense(int64_t h, const char* name, const float* data,
                            int64_t rows, int64_t cols) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = backend();
  if (mod) {
    PyObject* bytes = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(data),
        static_cast<Py_ssize_t>(rows * cols * sizeof(float)));
    PyObject* np = PyImport_ImportModule("numpy");
    PyObject* arr = nullptr;
    if (np && bytes) {
      PyObject* flat = PyObject_CallMethod(np, "frombuffer", "Os", bytes,
                                           "float32");
      if (flat) {
        arr = PyObject_CallMethod(flat, "reshape", "(LL)",
                                  static_cast<long long>(rows),
                                  static_cast<long long>(cols));
        Py_DECREF(flat);
      }
    }
    if (arr) {
      PyObject* r = PyObject_CallMethod(mod, "set_input_dense", "LsO",
                                        static_cast<long long>(h), name, arr);
      if (r && PyLong_Check(r)) rc = static_cast<int>(PyLong_AsLong(r));
      if (!r) PyErr_Print();
      Py_XDECREF(r);
    }
    Py_XDECREF(arr);
    Py_XDECREF(np);
    Py_XDECREF(bytes);
    if (rc != 0) capture_py_error();
  }
  PyGILState_Release(gil);
  return rc;
}

// Integer-id input [rows] (lengths == NULL) or a padded id sequence batch
// [rows, cols] with per-row lengths.
int pt_capi_set_input_ids(int64_t h, const char* name, const int32_t* ids,
                          int64_t rows, int64_t cols,
                          const int32_t* lengths) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = backend();
  if (mod) {
    PyObject* np = PyImport_ImportModule("numpy");
    PyObject* bytes = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(ids),
        static_cast<Py_ssize_t>(rows * (cols > 0 ? cols : 1) *
                                sizeof(int32_t)));
    PyObject* arr = nullptr;
    if (np && bytes) {
      PyObject* flat = PyObject_CallMethod(np, "frombuffer", "Os", bytes,
                                           "int32");
      if (flat) {
        if (cols > 0) {
          arr = PyObject_CallMethod(flat, "reshape", "(LL)",
                                    static_cast<long long>(rows),
                                    static_cast<long long>(cols));
          Py_DECREF(flat);
        } else {
          arr = flat;
        }
      }
    }
    PyObject* lens = Py_None;
    Py_INCREF(Py_None);
    if (lengths && cols > 0) {
      PyObject* lb = PyBytes_FromStringAndSize(
          reinterpret_cast<const char*>(lengths),
          static_cast<Py_ssize_t>(rows * sizeof(int32_t)));
      if (np && lb) {
        Py_DECREF(lens);
        lens = PyObject_CallMethod(np, "frombuffer", "Os", lb, "int32");
      }
      Py_XDECREF(lb);
    }
    if (arr && lens) {
      PyObject* r = PyObject_CallMethod(mod, "set_input_ids", "LsOO",
                                        static_cast<long long>(h), name, arr,
                                        lens);
      if (r && PyLong_Check(r)) rc = static_cast<int>(PyLong_AsLong(r));
      if (!r) PyErr_Print();
      Py_XDECREF(r);
    }
    Py_XDECREF(lens);
    Py_XDECREF(arr);
    Py_XDECREF(np);
    Py_XDECREF(bytes);
    if (rc != 0) capture_py_error();
  }
  PyGILState_Release(gil);
  return rc;
}

// Sparse-binary input in CSR form (row_offsets: rows+1 entries;
// col_ids[row_offsets[i]..row_offsets[i+1]) = set columns of row i),
// densified to [rows, dim] on the Python side.
int pt_capi_set_input_sparse_binary(int64_t h, const char* name, int64_t dim,
                                    const int32_t* col_ids, int64_t n_cols,
                                    const int32_t* row_offsets,
                                    int64_t n_offsets) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = backend();
  if (mod) {
    PyObject* np = PyImport_ImportModule("numpy");
    PyObject* cb = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(col_ids),
        static_cast<Py_ssize_t>(n_cols * sizeof(int32_t)));
    PyObject* rb = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(row_offsets),
        static_cast<Py_ssize_t>(n_offsets * sizeof(int32_t)));
    PyObject* cols = nullptr;
    PyObject* offs = nullptr;
    if (np && cb && rb) {
      cols = PyObject_CallMethod(np, "frombuffer", "Os", cb, "int32");
      offs = PyObject_CallMethod(np, "frombuffer", "Os", rb, "int32");
    }
    if (cols && offs) {
      PyObject* r = PyObject_CallMethod(
          mod, "set_input_sparse_binary", "LsLOO", static_cast<long long>(h),
          name, static_cast<long long>(dim), cols, offs);
      if (r && PyLong_Check(r)) rc = static_cast<int>(PyLong_AsLong(r));
      if (!r) PyErr_Print();
      Py_XDECREF(r);
    }
    Py_XDECREF(offs);
    Py_XDECREF(cols);
    Py_XDECREF(rb);
    Py_XDECREF(cb);
    Py_XDECREF(np);
    if (rc != 0) capture_py_error();
  }
  PyGILState_Release(gil);
  return rc;
}

// New handle sharing h's loaded parameters (reference
// paddle_gradient_machine_create_shared_param): per-thread machines over
// one parameter set.  Feed/output slots are per-handle.
int64_t pt_capi_clone(int64_t h) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int64_t handle = -1;
  PyObject* mod = backend();
  if (mod) {
    PyObject* r = PyObject_CallMethod(mod, "clone_shared", "L",
                                      static_cast<long long>(h));
    if (r && PyLong_Check(r)) handle = PyLong_AsLongLong(r);
    if (!r) PyErr_Print();
    Py_XDECREF(r);
    if (handle < 0) capture_py_error();
  }
  PyGILState_Release(gil);
  return handle;
}

// Run forward.  Returns the number of outputs, or -1.
int pt_capi_run(int64_t h) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = backend();
  if (mod) {
    PyObject* r = PyObject_CallMethod(mod, "run", "L",
                                      static_cast<long long>(h));
    if (r && PyLong_Check(r)) rc = static_cast<int>(PyLong_AsLong(r));
    if (!r) PyErr_Print();
    Py_XDECREF(r);
    if (rc < 0) capture_py_error();
  }
  PyGILState_Release(gil);
  return rc;
}

// Shape of output idx as [rows, cols] (row-flattened trailing dims).
int pt_capi_output_shape(int64_t h, int idx, int64_t* rows, int64_t* cols) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = backend();
  if (mod) {
    PyObject* r = PyObject_CallMethod(mod, "output_shape", "Li",
                                      static_cast<long long>(h), idx);
    if (r && PySequence_Check(r) && PySequence_Size(r) == 2) {
      PyObject* a = PySequence_GetItem(r, 0);
      PyObject* b = PySequence_GetItem(r, 1);
      *rows = PyLong_AsLongLong(PyNumber_Long(a));
      *cols = PyLong_AsLongLong(PyNumber_Long(b));
      Py_XDECREF(a);
      Py_XDECREF(b);
      rc = (*rows >= 0) ? 0 : -1;
    }
    if (!r) PyErr_Print();
    Py_XDECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

// Copy output idx into buf (float32, rows*cols elements).
int pt_capi_get_output(int64_t h, int idx, float* buf, int64_t capacity) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = backend();
  if (mod) {
    PyObject* r = PyObject_CallMethod(mod, "get_output", "Li",
                                      static_cast<long long>(h), idx);
    if (r && PyBytes_Check(r)) {
      Py_ssize_t n = PyBytes_Size(r);
      if (n <= capacity * static_cast<Py_ssize_t>(sizeof(float))) {
        std::memcpy(buf, PyBytes_AsString(r), n);
        rc = static_cast<int>(n / sizeof(float));
      } else {
        g_last_error = "output buffer too small";
      }
    }
    if (!r) PyErr_Print();
    Py_XDECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

int pt_capi_destroy(int64_t h) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* mod = backend();
  if (mod) {
    PyObject* r = PyObject_CallMethod(mod, "destroy", "L",
                                      static_cast<long long>(h));
    Py_XDECREF(r);
  }
  PyGILState_Release(gil);
  return 0;
}

}  // extern "C"
