"""paddle.v2.optimizer (reference v2/optimizer.py): class-style ctors over
the optim suite."""

from paddle_tpu.optim import (Momentum, Adam, AdaGrad, AdaDelta, RMSProp,
                              DecayedAdaGrad, AdaMax)


def _with_reg(ctor):
    def make(learning_rate=1e-3, regularization=None,
             gradient_clipping_threshold=None, model_average=None, **kw):
        if regularization:
            kw.setdefault("l2", regularization.get("l2", 0.0))
            kw.setdefault("l1", regularization.get("l1", 0.0))
        if gradient_clipping_threshold:
            kw.setdefault("clip_threshold", gradient_clipping_threshold)
        return ctor(learning_rate=learning_rate, **kw)
    return make


Momentum = _with_reg(Momentum)
Adam = _with_reg(Adam)
AdaGrad = _with_reg(AdaGrad)
AdaDelta = _with_reg(AdaDelta)
RMSProp = _with_reg(RMSProp)
DecayedAdaGrad = _with_reg(DecayedAdaGrad)
AdaMax = _with_reg(AdaMax)


def L2Regularization(rate):
    """paddle.v2.optimizer.L2Regularization(rate=...)"""
    return {"l2": rate}


def L1Regularization(rate):
    return {"l1": rate}
