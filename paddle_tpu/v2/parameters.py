"""paddle.v2.parameters (reference v2/parameters.py): a numpy-dict view of
the parameter pytree with create(cost) and to_tar/from_tar serialization."""

import io
import json
import tarfile

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.layers.graph import Topology


class Parameters:
    """Dict-like over flattened 'layer.param' names (the reference exposed
    flat parameter names like '___fc_layer_0__.w0')."""

    def __init__(self, tree):
        self.tree = tree

    # -------------------------------------------------- dict-like access
    def _flat(self):
        out = {}
        for path, leaf in jax.tree_util.tree_leaves_with_path(self.tree):
            name = ".".join(str(getattr(p, "key", getattr(p, "idx", p)))
                            for p in path)
            out[name] = leaf
        return out

    def names(self):
        return list(self._flat())

    def keys(self):
        return self.names()

    def __contains__(self, name):
        return name in self._flat()

    def __getitem__(self, name):
        return np.asarray(self._flat()[name])

    def __setitem__(self, name, value):
        parts = name.split(".")

        def setter(node, remaining):
            key = remaining[0]
            if isinstance(node, list):
                key = int(key)
            if len(remaining) == 1:
                node[key] = jnp.asarray(value)
            else:
                setter(node[key], remaining[1:])
        setter(self.tree, parts)

    def get_shape(self, name):
        return tuple(self._flat()[name].shape)

    # -------------------------------------------------- serialization
    def to_tar(self, f):
        """Reference v2/parameters.py to_tar: tar of raw arrays + meta."""
        with tarfile.open(fileobj=f, mode="w") as tar:
            flat = self._flat()
            meta = {}
            for name, arr in flat.items():
                a = np.asarray(arr)
                meta[name] = {"shape": list(a.shape), "dtype": str(a.dtype)}
                data = a.tobytes()
                info = tarfile.TarInfo(name=name)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
            mb = json.dumps(meta).encode()
            info = tarfile.TarInfo(name="__meta__.json")
            info.size = len(mb)
            tar.addfile(info, io.BytesIO(mb))

    @classmethod
    def from_tar(cls, f, like=None):
        """Returns a flat {name: np.ndarray}; with like= (a Parameters or
        pytree) the arrays are written into a copy of that tree."""
        with tarfile.open(fileobj=f, mode="r") as tar:
            meta = json.loads(tar.extractfile("__meta__.json").read())
            flat = {}
            for name, m in meta.items():
                raw = tar.extractfile(name).read()
                flat[name] = np.frombuffer(raw, m["dtype"]).reshape(
                    m["shape"])
        if like is None:
            return flat
        tree = like.tree if isinstance(like, Parameters) else like
        params = cls(jax.tree_util.tree_map(jnp.asarray, tree))
        for name, arr in flat.items():
            params[name] = arr
        return params


def create(cost, seed=1):
    """paddle.v2.parameters.create(cost) -> Parameters."""
    outs = cost if isinstance(cost, (list, tuple)) else [cost]
    topo = Topology(list(outs))
    return Parameters(topo.init(jax.random.PRNGKey(seed)))
