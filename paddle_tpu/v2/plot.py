"""paddle.v2.plot (reference python/paddle/v2/plot/plot.py): the Ploter
notebook helper — named curves appended per step, redrawn on plot().
DISABLE_PLOT=True turns plotting into a cheap print (the reference used the
same env switch for headless test conversion)."""

import os

__all__ = ["Ploter", "PlotData"]


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {title: PlotData() for title in args}

    def __plot_is_disabled__(self):
        return os.environ.get("DISABLE_PLOT") == "True"

    def append(self, title, step, value):
        self.__plot_data__[title].append(step, float(value))

    def plot(self, path=None):
        if self.__plot_is_disabled__():
            for title, data in self.__plot_data__.items():
                if data.step:
                    print(f"{title}: step {data.step[-1]} "
                          f"value {data.value[-1]:.6g}")
            return
        import matplotlib
        if path:   # headless save
            matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        titles = []
        for title, data in self.__plot_data__.items():
            if data.step:
                plt.plot(data.step, data.value)
                titles.append(title)
        plt.legend(titles, loc="upper left")
        if path:
            plt.savefig(path)
            plt.close()
        else:     # notebook flow: clear + draw
            try:
                from IPython import display
                display.clear_output(wait=True)
            except Exception:
                pass
            plt.pause(0.001)

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
