"""paddle.v2.topology (reference v2/topology.py:1).

The reference's Topology wrapped the serialized ModelConfig proto and
answered get_layer/data_type queries; here the graph IR Topology IS that
object, re-exported with the reference's name and the proto-era helpers on
the IR (layer lookup by name, data-layer enumeration via .order).
"""

from paddle_tpu.layers.graph import Topology  # noqa: F401
