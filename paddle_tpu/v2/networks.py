"""paddle.v2.networks (reference v2/networks.py re-exporting
trainer_config_helpers.networks)."""

from paddle_tpu.layers.networks import *          # noqa: F401,F403
from paddle_tpu.layers.networks import __all__    # noqa: F401
