"""paddle.v2.inference (reference v2/inference.py:11-73)."""

from paddle_tpu.trainer.trainer import Inferencer


def infer(output_layer, parameters, input, feeding=None):
    from paddle_tpu.v2.parameters import Parameters
    tree = parameters.tree if isinstance(parameters, Parameters) \
        else parameters
    return Inferencer(output_layer, tree).infer(input, feeding=feeding)
