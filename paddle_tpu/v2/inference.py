"""paddle.v2.inference (reference v2/inference.py:11-73), routed through
the serving runtime's bucketed AOT engine.

The reference's ``Inference`` wrapped the GradientMachine in test mode;
here it wraps ``serving.InferenceEngine``: the forward is AOT-compiled
once per batch bucket (ladder 1/4/16/64 by default), each ``infer`` batch
pads to the nearest bucket and slices back, and repeated calls at ragged
batch sizes never retrace.  Buckets compile lazily (first use), so a
one-shot ``infer`` costs one compile exactly like the old direct path.

Sequence slots pad per batch, so every distinct padded length is its own
row signature and needs its own bucket ladder.  The per-signature engine
table is a bounded LRU (``max_engines``, default 8): under ragged lengths
it can no longer grow without limit — the least-recently-used engine
(and its compiled executables) is dropped, counted in
``metrics.engine_cache_evictions`` and surfaced at ``/metrics`` as
``engine_cache_evictions_total``.  An evicted signature that returns
simply recompiles on first use, like any cold bucket.

Row results are independent of padding and co-batched rows, so routing
through the engine is a pure execution change — outputs match the direct
forward bit-for-bit (tests/test_serving.py parity test).
"""

from collections import OrderedDict

from paddle_tpu.trainer.trainer import Inferencer, _normalize_feed
from paddle_tpu.data.feeder import DataFeeder


class Inference:
    """v2-style inference object over the bucketed engine.

    output_layer: LayerOutput (or list); parameters: v2 Parameters or a
    raw pytree; buckets: batch ladder (default serving.DEFAULT_BUCKETS);
    larger batches chunk at the ladder top; max_engines: bound on the
    per-row-signature engine LRU (>= 1)."""

    def __init__(self, output_layer, parameters, model_state=None,
                 buckets=None, max_engines=8):
        from paddle_tpu.v2.parameters import Parameters
        from paddle_tpu.serving import ServingMetrics
        tree = parameters.tree if isinstance(parameters, Parameters) \
            else parameters
        self._inferencer = Inferencer(output_layer, tree,
                                      model_state=model_state)
        self._buckets = buckets
        if int(max_engines) < 1:
            raise ValueError("max_engines must be >= 1")
        self._max_engines = int(max_engines)
        # ONE metrics object across every signature's engine, so the
        # eviction counter (and batch/latency stats) survive evictions
        self.metrics = ServingMetrics()
        self._engines = OrderedDict()   # row signature -> engine (LRU)

    def _engine_for(self, feed):
        import numpy as np
        import jax
        from paddle_tpu.serving import DEFAULT_BUCKETS, InferenceEngine
        leaves, treedef = jax.tree_util.tree_flatten(feed)
        sig = (treedef, tuple((tuple(np.shape(l)[1:]), np.dtype(l.dtype))
                              for l in leaves))
        eng = self._engines.get(sig)
        if eng is not None:
            self._engines.move_to_end(sig)      # most recently used
            return eng
        eng = self._engines[sig] = InferenceEngine.from_inferencer(
            self._inferencer, feed_spec=feed,
            buckets=self._buckets or DEFAULT_BUCKETS,
            warm=False, name="v2.infer", metrics=self.metrics)
        while len(self._engines) > self._max_engines:
            self._engines.popitem(last=False)   # least recently used
            self.metrics.evict_engine_cache()
        return eng

    def infer(self, input, feeding=None):
        if feeding is not None and not isinstance(input, dict):
            feeder = feeding if isinstance(feeding, DataFeeder) \
                else DataFeeder(feeding)
            feed = feeder(input)
        else:
            feed = input
        feed = _normalize_feed(feed)
        return self._engine_for(feed).infer(feed)


def infer(output_layer, parameters, input, feeding=None):
    return Inference(output_layer, parameters).infer(input, feeding=feeding)
