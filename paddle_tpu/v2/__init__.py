"""paddle.v2-compatible namespace.

Reference: python/paddle/v2/ (layer.py, trainer.py:30 SGD, parameters.py,
optimizer.py, event.py, inference.py, reader/, dataset/, minibatch.py).
A reference user's `import paddle.v2 as paddle` script maps to
`import paddle_tpu.v2 as paddle` with the same module shapes:

    paddle.init(use_gpu=False, trainer_count=1)
    y = paddle.layer.fc(input=x, size=10, act=...)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(cost, params, paddle.optimizer.Adam(...))
    trainer.train(reader=paddle.batch(paddle.dataset.mnist.train(), 128),
                  event_handler=..., num_passes=5)
"""

from paddle_tpu.trainer.api import init
from paddle_tpu.v2.inference import infer
from paddle_tpu.v2 import config_base, minibatch, topology  # noqa: F401

from paddle_tpu.v2 import layer
from paddle_tpu.v2 import activation
from paddle_tpu.v2 import pooling
from paddle_tpu.v2 import attr
from paddle_tpu.v2 import networks
from paddle_tpu.v2 import optimizer
from paddle_tpu.v2 import parameters
from paddle_tpu.v2 import trainer
from paddle_tpu.v2 import event
from paddle_tpu.v2 import inference
from paddle_tpu.v2 import reader
from paddle_tpu.v2 import dataset
from paddle_tpu.v2 import evaluator
from paddle_tpu.v2 import plot
from paddle_tpu.data import feeder as data_feeder
# NB: paddle_tpu.data re-binds the name `provider` to the decorator
# *function*, which shadows the submodule for `import ... as` — resolve the
# module through sys.modules instead
import importlib as _importlib
data_type = _importlib.import_module("paddle_tpu.data.provider")


# paddle.batch IS minibatch.batch (one definition, two reference names)
batch = minibatch.batch


__all__ = ["init", "infer", "batch", "layer", "activation", "pooling",
           "attr", "networks", "optimizer", "parameters", "trainer",
           "event", "inference", "reader", "dataset", "evaluator",
           "data_feeder", "data_type"]
