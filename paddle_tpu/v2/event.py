"""paddle.v2.event (reference v2/event.py)."""

from paddle_tpu.trainer.events import (      # noqa: F401
    BeginPass, EndPass, BeginIteration, EndIteration, EndTesting)

# the reference calls the test-result event TestResult
TestResult = EndTesting
