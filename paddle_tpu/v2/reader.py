"""paddle.v2.reader (reference v2/reader/decorator.py)."""

from paddle_tpu.data.reader import (        # noqa: F401
    map_readers, shuffle, buffered, batch, compose, chain, firstn)
