"""paddle.v2.dataset (reference v2/dataset/: mnist, cifar, imdb, imikolov,
movielens, conll05, uci_housing, wmt14 with auto-download+cache; this
image has zero egress so loaders fall back to deterministic synthetic data
with the real schemas — see data/datasets/_synth.py)."""

from paddle_tpu.data.datasets import (      # noqa: F401
    mnist, cifar, imdb, imikolov, movielens, conll05, uci_housing, wmt14)
