"""paddle.v2.dataset (reference v2/dataset/: mnist, cifar, imdb, imikolov,
movielens, conll05, uci_housing, wmt14 with auto-download+cache via
common.download).  Real files load from PADDLE_TPU_DATA_DIR; without them
(or network for common.download) loaders fall back to deterministic
synthetic data with the real schemas — see data/datasets/_synth.py."""

from paddle_tpu.data.datasets import (      # noqa: F401
    common, mnist, cifar, imdb, imikolov, movielens, conll05, sentiment,
    uci_housing, wmt14)
