"""paddle.v2.evaluator (reference v2/evaluator.py re-exporting the
evaluator ctors)."""

from paddle_tpu.evaluators.dsl import *     # noqa: F401,F403
from paddle_tpu.evaluators.dsl import __all__  # noqa: F401
