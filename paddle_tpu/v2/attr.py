"""paddle.v2.attr: ParamAttr/ExtraAttr (reference v2/attr.py wrapping
trainer_config_helpers/attrs.py).  Layer ctors accept plain dicts; these
helpers build them."""


def Param(name=None, initial_std=None, initial_mean=None, learning_rate=None,
          l2_rate=None, l1_rate=None, is_static=False, initial_strategy=None,
          **kw):
    d = {}
    if name is not None:
        d["name"] = name
    if initial_std is not None:
        d["initial_std"] = initial_std
    if initial_mean is not None:
        d["initial_mean"] = initial_mean
    if initial_strategy is not None:
        d["initial_strategy"] = initial_strategy
    if learning_rate is not None:
        d["learning_rate"] = learning_rate
    if l2_rate is not None:
        d["l2_rate"] = l2_rate
    if l1_rate is not None:
        d["l1_rate"] = l1_rate
    if is_static:
        d["is_static"] = True
    d.update(kw)
    return d


ParamAttr = Param


def Extra(drop_rate=None, **kw):
    d = {}
    if drop_rate is not None:
        d["drop_rate"] = drop_rate
    d.update(kw)
    return d


ExtraAttr = ExtraLayerAttribute = Extra

from paddle_tpu.compat.v1 import HookAttribute  # noqa: E402

Hook = HookAttr = HookAttribute
