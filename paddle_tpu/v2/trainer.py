"""paddle.v2.trainer (reference v2/trainer.py:30): SGD with the v2 call
shape — SGD(cost, parameters, update_equation).train(reader,
event_handler, num_passes)."""

from paddle_tpu.trainer.trainer import SGD as _SGD


class SGD(_SGD):
    def __init__(self, cost, parameters=None, update_equation=None,
                 extra_layers=None, is_local=True, **kw):
        from paddle_tpu.v2.parameters import Parameters
        tree = parameters.tree if isinstance(parameters, Parameters) \
            else parameters
        super().__init__(cost, parameters=tree,
                         update_equation=update_equation,
                         extra_layers=extra_layers, is_local=is_local, **kw)
        self._v2_parameters = parameters

    def train(self, reader, num_passes=1, event_handler=None, feeding=None,
              **kw):
        super().train(reader, num_passes=num_passes,
                      event_handler=event_handler, feeding=feeding, **kw)
        # keep the user's Parameters view aliased to the trained tree
        if self._v2_parameters is not None:
            self._v2_parameters.tree = self.parameters
