"""paddle.v2.pooling (reference v2/pooling.py)."""

from paddle_tpu.layers import pooling as _p

Max = _p.Max
Avg = _p.Avg
Sum = _p.Sum
SquareRootN = getattr(_p, "SquareRootN", _p.Avg)
