"""paddle.v2.minibatch (reference v2/minibatch.py:1): batch(reader, size)."""

from paddle_tpu.data.reader import batch  # noqa: F401
