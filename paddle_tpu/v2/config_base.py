"""paddle.v2.config_base (reference v2/config_base.py:1).

The reference's Layer base class adapted v1 config funcs into v2 graph
objects; the rebuild's layer ctors already return graph nodes
(LayerOutput), so that class IS the base surface here.
"""

from paddle_tpu.layers.graph import LayerOutput as Layer  # noqa: F401
