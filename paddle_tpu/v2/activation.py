"""paddle.v2.activation: class-style activation markers (reference
v2/activation.py wrapping trainer_config_helpers/activations.py).  Layer
ctors here take act= strings, so these classes stringify to their name."""


class _Act(str):
    def __new__(cls, name):
        return str.__new__(cls, name)


Tanh = _Act("tanh")
Sigmoid = _Act("sigmoid")
Softmax = _Act("softmax")
SequenceSoftmax = _Act("sequence_softmax")
Relu = _Act("relu")
BRelu = _Act("brelu")
SoftRelu = _Act("softrelu")
STanh = _Act("stanh")
Abs = _Act("abs")
Square = _Act("square")
Exp = _Act("exponential")
Log = _Act("log")
Linear = Identity = _Act("")


def __getattr__(name):
    raise AttributeError(f"unknown activation {name!r}")
