"""paddle.v2.layer: the v1 ctors re-exported without the `_layer` suffix
(reference v2/layer.py re-exports via config_base)."""

import paddle_tpu.layers as _L

# v2 names drop the _layer suffix: paddle.layer.fc, .data, .embedding, ...
data = _L.data_layer
fc = _L.fc_layer
embedding = _L.embedding_layer
conv = img_conv = _L.img_conv_layer
pool = img_pool = _L.img_pool_layer
batch_norm = _L.batch_norm_layer
dropout = _L.dropout_layer
addto = _L.addto_layer
concat = _L.concat_layer
mixed = _L.mixed_layer
lstmemory = _L.lstmemory
grumemory = _L.grumemory
recurrent = _L.recurrent_layer
recurrent_group = _L.recurrent_group
memory = _L.memory
beam_search = _L.beam_search
GeneratedInput = _L.GeneratedInput
StaticInput = _L.StaticInput
pooling = _L.pooling_layer
last_seq = _L.last_seq
first_seq = _L.first_seq
expand = _L.expand_layer
seq_concat = _L.seq_concat_layer
seq_reshape = _L.seq_reshape_layer
max_id = _L.maxid_layer
eos = _L.eos_layer
cross_entropy_cost = _L.cross_entropy
classification_cost = _L.classification_cost
regression_cost = square_error_cost = mse_cost = _L.regression_cost
crf = _L.crf_layer
crf_decoding = _L.crf_decoding_layer
ctc = _L.ctc_layer
warp_ctc = _L.warp_ctc_layer
nce = _L.nce_layer
hsigmoid = _L.hsigmoid
rank_cost = _L.rank_cost
lambda_cost = _L.lambda_cost
huber_cost = _L.huber_cost
sum_cost = _L.sum_cost
cos_sim = _L.cos_sim
trans = _L.trans_layer
rotate = _L.rotate_layer
tensor = _L.tensor_layer
scaling = _L.scaling_layer
slope_intercept = _L.slope_intercept_layer
interpolation = _L.interpolation_layer
power = _L.power_layer
sampling_id = _L.sampling_id_layer
maxout = _L.maxout_layer
spp = _L.spp_layer
pad = _L.pad_layer
bilinear_interp = _L.bilinear_interp_layer
block_expand = _L.block_expand_layer
img_cmrnorm = _L.img_cmrnorm_layer
sum_to_one_norm = _L.sum_to_one_norm_layer
repeat = _L.repeat_layer

# projections/operators keep their names
full_matrix_projection = _L.full_matrix_projection
trans_full_matrix_projection = _L.trans_full_matrix_projection
identity_projection = _L.identity_projection
table_projection = _L.table_projection
dotmul_projection = _L.dotmul_projection
scaling_projection = _L.scaling_projection
context_projection = _L.context_projection
conv_projection = _L.conv_projection
dotmul_operator = _L.dotmul_operator
conv_operator = _L.conv_operator

AggregateLevel = _L.AggregateLevel
ExpandLevel = _L.ExpandLevel
LayerOutput = _L.LayerOutput
