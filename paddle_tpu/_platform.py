"""Single implementation of the JAX_PLATFORMS env re-assert.

Some deployment images register a TPU plugin from sitecustomize and pin the
jax_platforms CONFIG at interpreter startup, silently overriding the user's
JAX_PLATFORMS env var (symptom: CPU-intended runs hang on a remote TPU
tunnel).  Call this before the first backend use to restore standard JAX
env semantics.  Kept dependency-free so the package root can import it
first.
"""

import os
import warnings


def honor_jax_platforms_env():
    """Re-assert JAX_PLATFORMS (full priority list, e.g. "tpu,cpu") at the
    config level.  Failure (backend already initialized) warns instead of
    silently leaving the user on the wrong platform."""
    plats = os.environ.get("JAX_PLATFORMS")
    if not plats:
        return
    try:
        import jax
        jax.config.update("jax_platforms", plats)
    except Exception as e:   # noqa: BLE001
        warnings.warn(
            f"JAX_PLATFORMS={plats!r} could not be applied to jax config "
            f"({type(e).__name__}: {e}); the process may be routed to a "
            "different backend than the env var requests", stacklevel=2)
