"""Functional beam-search decoder.

Reference: RecurrentGradientMachine::generateSequence/beamSearch
(RecurrentGradientMachine.cpp:823,1248) with beamExpand :1101 / beamShrink
:1127 and user hooks (candidate adjust / per-node drop / eos,
RecurrentGradientMachine.h:87-177).

TPU design: static beam_size and max_len, one `lax.scan` over decode steps;
the reference's dynamic Path lists become fixed [B, K] lanes with a finished
mask; state gathering ("copy scattered memory-layer states per surviving
path", machineIdVec) is a batched `take_along_axis` on the state pytree.
Length-normalized scoring and the eos/drop callback semantics are kept.
"""

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

_NEG = -1e30


class BeamResult(NamedTuple):
    tokens: jnp.ndarray    # [B, K, T] int32 (eos_id-padded after finish)
    scores: jnp.ndarray    # [B, K] total log-prob (normalized if asked)
    lengths: jnp.ndarray   # [B, K] tokens before (excluding) eos


def beam_search(step_fn: Callable, init_state: Any, batch_size: int,
                beam_size: int, max_len: int, bos_id: int, eos_id: int,
                length_penalty: float = 0.0,
                candidate_adjust: Optional[Callable] = None,
                drop_callback: Optional[Callable] = None):
    """step_fn(state, prev_ids [B*K]) -> (log_probs [B*K, V], new_state).

    State leaves are [B*K, ...] (lane-major).  candidate_adjust(log_probs)
    optionally rewrites per-step candidate scores (the reference's
    calc_id_interest / candidate adjust hook).

    drop_callback(tokens [B, K, T], t, cand [B, K, V]) -> cand: the
    reference's per-node NormOrDropNodeCallback
    (RecurrentGradientMachine.h:87-177) — sees each lane's decoded prefix
    and the expanded candidate scores at step t, and may renormalize them
    or drop nodes by writing -inf; dropped expansions never enter top-k
    (the static-shape equivalent of removing the Path in beamExpand).

    Returns BeamResult sorted best-first per batch row.
    """
    bk = batch_size * beam_size

    def gather_state(state, src_lane):
        """src_lane: [B, K] index into K lanes; reindex every state leaf."""
        flat_idx = (jnp.arange(batch_size)[:, None] * beam_size
                    + src_lane).reshape(-1)
        return jax.tree_util.tree_map(
            lambda x: jnp.take(x, flat_idx, axis=0), state)

    init_tokens = jnp.full((batch_size, beam_size, max_len), eos_id, jnp.int32)
    # lane 0 active, others dead (so the first expansion is over V not K*V)
    init_scores = jnp.tile(
        jnp.asarray([0.0] + [_NEG] * (beam_size - 1))[None, :],
        (batch_size, 1))
    init_finished = jnp.zeros((batch_size, beam_size), bool)
    init_prev = jnp.full((bk,), bos_id, jnp.int32)
    init_len = jnp.zeros((batch_size, beam_size), jnp.int32)

    def body(carry, t):
        state, prev, tokens, scores, finished, lengths = carry
        log_probs, new_state = step_fn(state, prev)
        if candidate_adjust is not None:
            log_probs = candidate_adjust(log_probs)
        v = log_probs.shape[-1]
        lp = log_probs.reshape(batch_size, beam_size, v)

        # finished lanes: only continuing with eos at zero cost keeps score
        eos_only = jnp.full((v,), _NEG).at[eos_id].set(0.0)
        lp = jnp.where(finished[..., None], eos_only[None, None, :], lp)

        cand = scores[..., None] + lp                       # [B, K, V]
        if drop_callback is not None:
            # never drop the eos continuation of an already-finished lane
            # (it carries the lane's final score, not a real expansion)
            adjusted = drop_callback(tokens, t, cand)
            keep_eos = finished[..., None] & (
                jnp.arange(v)[None, None, :] == eos_id)
            cand = jnp.where(keep_eos, cand, adjusted)
        flat = cand.reshape(batch_size, beam_size * v)
        top_scores, top_idx = jax.lax.top_k(flat, beam_size)  # [B, K]
        src_lane = (top_idx // v).astype(jnp.int32)
        new_tok = (top_idx % v).astype(jnp.int32)

        # reorder histories and state by surviving lanes
        tokens = jnp.take_along_axis(tokens, src_lane[..., None], axis=1)
        tokens = tokens.at[:, :, t].set(new_tok)
        was_finished = jnp.take_along_axis(finished, src_lane, axis=1)
        lengths = jnp.take_along_axis(lengths, src_lane, axis=1)
        now_finished = was_finished | (new_tok == eos_id)
        lengths = jnp.where(was_finished, lengths,
                            jnp.where(new_tok == eos_id, lengths, lengths + 1))
        state = gather_state(new_state, src_lane)
        prev = new_tok.reshape(-1)
        return (state, prev, tokens, top_scores, now_finished, lengths), None

    carry = (init_state, init_prev, init_tokens, init_scores, init_finished,
             init_len)
    (state, prev, tokens, scores, finished, lengths), _ = jax.lax.scan(
        body, carry, jnp.arange(max_len))

    if length_penalty:
        norm = ((5.0 + lengths.astype(scores.dtype)) / 6.0) ** length_penalty
        scores = scores / jnp.maximum(norm, 1e-6)
    order = jnp.argsort(-scores, axis=1)
    tokens = jnp.take_along_axis(tokens, order[..., None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    lengths = jnp.take_along_axis(lengths, order, axis=1)
    return BeamResult(tokens=tokens, scores=scores, lengths=lengths)


def greedy_search(step_fn, init_state, batch_size, max_len, bos_id, eos_id):
    """Reference oneWaySearch (:900): argmax decode."""
    def body(carry, t):
        state, prev, tokens, finished, lengths = carry
        log_probs, state = step_fn(state, prev)
        nxt = jnp.argmax(log_probs, axis=-1).astype(jnp.int32)
        nxt = jnp.where(finished, eos_id, nxt)
        tokens = tokens.at[:, t].set(nxt)
        lengths = jnp.where(finished | (nxt == eos_id), lengths, lengths + 1)
        finished = finished | (nxt == eos_id)
        return (state, nxt, tokens, finished, lengths), None

    tokens0 = jnp.full((batch_size, max_len), eos_id, jnp.int32)
    carry = (init_state, jnp.full((batch_size,), bos_id, jnp.int32), tokens0,
             jnp.zeros((batch_size,), bool), jnp.zeros((batch_size,), jnp.int32))
    (state, _, tokens, finished, lengths), _ = jax.lax.scan(
        body, carry, jnp.arange(max_len))
    return tokens, lengths
