"""Convolution / vision ops, NHWC (TPU-native layout).

Reference CNN stack (SURVEY.md §2.2 "Conv/vision"): ExpandConvLayer (im2col)
and CudnnConvLayer, PoolLayer/CudnnPoolLayer (max/avg), NormLayer (LRN
cross-map), MaxOutLayer, BilinearInterpLayer, BlockExpandLayer,
SpatialPyramidPoolLayer, PadLayer, conv output-size calc
(math/MathUtils.cpp outputSize).  The dual plain/cudnn variants collapse
into one XLA `conv_general_dilated` path that the compiler tiles onto the
MXU; im2col disappears.

Layout note: the reference flattens images row-major as [C, H, W] per sample.
All ops here take/return NHWC; layer wrappers do the flat<->NHWC reshapes.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes
from paddle_tpu.ops import activations

_DN = ("NHWC", "HWIO", "NHWC")


def _conv_call(x, w, cfg):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=cfg["stride"], padding=cfg["pad"],
        lhs_dilation=cfg["lhs_dilation"], rhs_dilation=cfg["rhs_dilation"],
        dimension_numbers=_DN, feature_group_count=cfg["groups"],
        preferred_element_type=cfg["preferred"])


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _conv_f32acc(x, w, cfg_key):
    """conv with f32 accumulation (preferred_element_type=f32) whose backward
    keeps operand dtypes uniform: JAX's conv transpose rule rejects mixed
    (f32 cotangent, bf16 operand) pairs, so the bwd casts the cotangent to
    the operand dtype and differentiates a same-dtype conv instead.  f64
    operands (gradient-check mode) keep their own precision."""
    preferred = None if x.dtype == jnp.float64 else jnp.float32
    return _conv_call(x, w, dict(cfg_key) | {"preferred": preferred})


def _conv_f32acc_fwd(x, w, cfg_key):
    return _conv_f32acc(x, w, cfg_key), (x, w)


def _conv_f32acc_bwd(cfg_key, res, g):
    x, w = res
    cfg = dict(cfg_key) | {"preferred": None}
    _, vjp = jax.vjp(lambda x_, w_: _conv_call(x_, w_, cfg), x, w)
    return vjp(g.astype(x.dtype))


_conv_f32acc.defvjp(_conv_f32acc_fwd, _conv_f32acc_bwd)


def _conv(x, w, stride, pad, lhs_dilation, rhs_dilation, groups):
    cfg_key = (("stride", tuple(stride)), ("pad", tuple(pad)),
               ("lhs_dilation", tuple(lhs_dilation) if lhs_dilation else None),
               ("rhs_dilation", tuple(rhs_dilation)), ("groups", groups))
    return _conv_f32acc(x, w, cfg_key)


def conv_output_size(in_size, filter_size, stride, padding):
    """Reference math/MathUtils.cpp outputSize (caffeMode=True):
    (in + 2*pad - filter) / stride + 1."""
    return (in_size + 2 * padding - filter_size) // stride + 1


def conv2d(x, w, b=None, stride=(1, 1), padding=(0, 0), groups=1,
           dilation=(1, 1), act=None):
    """x: [B, H, W, Cin], w: [kh, kw, Cin/groups, Cout] -> [B, H', W', Cout]."""
    cd = dtypes.compute_dtype()
    pad = ((padding[0], padding[0]), (padding[1], padding[1]))
    y = _conv(x.astype(cd), w.astype(cd), stride, pad, None, dilation, groups)
    if b is not None:
        y = y + b
    return activations.get(act)(y)


def conv2d_transpose(x, w, b=None, stride=(1, 1), padding=(0, 0), act=None):
    """Gradient-of-conv deconvolution (reference ExpandConvTransLayer).
    w: [kh, kw, Cout, Cin] stored like the forward conv's weight."""
    cd = dtypes.compute_dtype()
    kh, kw = w.shape[0], w.shape[1]
    pad = ((kh - 1 - padding[0], kh - 1 - padding[0]),
           (kw - 1 - padding[1], kw - 1 - padding[1]))
    y = _conv(x.astype(cd), jnp.flip(w, (0, 1)).swapaxes(2, 3).astype(cd),
              (1, 1), pad, stride, (1, 1), 1)
    if b is not None:
        y = y + b
    return activations.get(act)(y)


def _pool_pad(padding):
    """(ph, pw) symmetric or ((plo,phi),(plo,phi)) asymmetric (asymmetric
    covers the reference's ceil-mode output sizes)."""
    ph, pw = padding
    ph = ph if isinstance(ph, (tuple, list)) else (ph, ph)
    pw = pw if isinstance(pw, (tuple, list)) else (pw, pw)
    return ((0, 0), tuple(ph), tuple(pw), (0, 0))


def max_pool2d(x, window, stride=None, padding=(0, 0)):
    stride = stride or window
    pad = _pool_pad(padding)
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        (1, window[0], window[1], 1), (1, stride[0], stride[1], 1), pad)


def avg_pool2d(x, window, stride=None, padding=(0, 0), exclude_pad=True):
    stride = stride or window
    pad = _pool_pad(padding)
    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add,
        (1, window[0], window[1], 1), (1, stride[0], stride[1], 1), pad)
    if exclude_pad and any(p for dims in pad for p in dims):
        ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
        cnt = jax.lax.reduce_window(
            ones, 0.0, jax.lax.add,
            (1, window[0], window[1], 1), (1, stride[0], stride[1], 1), pad)
        return summed / jnp.maximum(cnt, 1.0)
    return summed / float(window[0] * window[1])


def lrn_cross_map(x, size=5, scale=1e-4, power=0.75):
    """Local response norm across channels (reference NormProjectionLayer,
    'cmrnorm-projection'): out = x * (1 + scale/size * sum(x^2))^-power."""
    sq = jnp.square(x)
    half = size // 2
    padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, size - 1 - half)))
    acc = jnp.cumsum(padded, axis=-1)
    zeros = jnp.zeros_like(acc[..., :1])
    acc = jnp.concatenate([zeros, acc], axis=-1)
    window = acc[..., size:] - acc[..., :-size]
    denom = (1.0 + (scale / size) * window) ** power
    return x / denom


def cross_channel_norm(x, scale):
    """L2-normalize across channels then scale per-channel (reference
    CrossChannelNormLayer, SSD)."""
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True) + 1e-10)
    return x / norm * scale


def maxout(x, groups):
    """Channel maxout (reference MaxOutLayer): Cout = Cin/groups."""
    b, h, w, c = x.shape
    return jnp.max(x.reshape(b, h, w, c // groups, groups), axis=-1)


def bilinear_interp(x, out_h, out_w):
    """Bilinear resize (reference BilinearInterpLayer)."""
    return jax.image.resize(x, (x.shape[0], out_h, out_w, x.shape[3]),
                            method="bilinear")


def pad_chw(x, pad_c=(0, 0), pad_h=(0, 0), pad_w=(0, 0)):
    """Reference PadLayer pads (C, H, W) of NCHW; here NHWC."""
    return jnp.pad(x, ((0, 0), pad_h, pad_w, pad_c))


def block_expand(x, block, stride, padding=(0, 0)):
    """im2col as a layer (reference BlockExpandLayer): NHWC ->
    [B, num_blocks, block_h*block_w*C] patch sequence."""
    bh, bw = block
    pad = ((0, 0), (padding[0], padding[0]), (padding[1], padding[1]), (0, 0))
    xp = jnp.pad(x, pad)
    patches = jax.lax.conv_general_dilated_patches(
        xp.transpose(0, 3, 1, 2), (bh, bw), stride, "VALID")
    # patches: [B, C*bh*bw, oh, ow]
    b, f, oh, ow = patches.shape
    return patches.reshape(b, f, oh * ow).transpose(0, 2, 1)


def adaptive_pool2d(x, bins, pool_type="max"):
    """Pool NHWC to an exact [B, bins, bins, C] regardless of input size
    (uneven windows like torch AdaptiveMaxPool; bins is static so the
    slice loop unrolls at trace time)."""
    b, h, w, c = x.shape
    reduce_fn = (lambda v: jnp.max(v, axis=(1, 2))) if pool_type == "max" \
        else (lambda v: jnp.mean(v, axis=(1, 2)))
    rows = []
    for i in range(bins):
        hs, he = (i * h) // bins, max(-(-((i + 1) * h) // bins), (i * h) // bins + 1)
        cols = []
        for j in range(bins):
            ws, we = (j * w) // bins, max(-(-((j + 1) * w) // bins), (j * w) // bins + 1)
            cols.append(reduce_fn(x[:, hs:he, ws:we, :]))
        rows.append(jnp.stack(cols, axis=1))
    return jnp.stack(rows, axis=1)  # [B, bins, bins, C]


def spatial_pyramid_pool(x, pyramid_height, pool_type="max"):
    """Reference SpatialPyramidPoolLayer: concat pooled maps at scales
    1x1, 2x2, ... 2^(h-1) bins.  Output width is fixed at
    C * sum(4^level) regardless of the input's spatial size — the whole
    point of SPP — via adaptive (uneven-window) pooling."""
    b = x.shape[0]
    outs = []
    for level in range(pyramid_height):
        bins = 2 ** level
        outs.append(adaptive_pool2d(x, bins, pool_type).reshape(b, -1))
    return jnp.concatenate(outs, axis=-1)


def prior_box(feature_shape, image_shape, min_sizes, max_sizes, aspect_ratios,
              variance=(0.1, 0.1, 0.2, 0.2), clip=True):
    """SSD prior boxes (reference PriorBox layer).  Pure numpy-style compute,
    returns [num_priors, 4(+4 var)] center-size encoded corners."""
    fh, fw = feature_shape
    ih, iw = image_shape
    step_h, step_w = ih / fh, iw / fw
    boxes = []
    for y in range(fh):
        for x in range(fw):
            cx, cy = (x + 0.5) * step_w, (y + 0.5) * step_h
            for k, ms in enumerate(min_sizes):
                boxes.append([cx - ms / 2, cy - ms / 2, cx + ms / 2, cy + ms / 2])
                if max_sizes:
                    sz = (ms * max_sizes[k]) ** 0.5
                    boxes.append([cx - sz / 2, cy - sz / 2, cx + sz / 2, cy + sz / 2])
                for ar in aspect_ratios:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    for a in (ar, 1.0 / ar):
                        bw_, bh_ = ms * a ** 0.5, ms / a ** 0.5
                        boxes.append([cx - bw_ / 2, cy - bh_ / 2,
                                      cx + bw_ / 2, cy + bh_ / 2])
    boxes = jnp.asarray(boxes)
    boxes = boxes / jnp.asarray([iw, ih, iw, ih])
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance), boxes.shape)
    return jnp.concatenate([boxes, var], axis=-1)
