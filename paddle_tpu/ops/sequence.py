"""Variable-length sequence ops over padded batches.

Reference zoo (SURVEY.md §2.2 "Sequence manipulation"): SequencePoolLayer
(MaxLayer/AverageLayer/SequenceLastInstanceLayer), ExpandLayer,
SequenceConcatLayer, SequenceReshapeLayer, SubSequenceLayer, ContextProjection
(function/ContextProjectionOp.cpp), EosIdCheckLayer, MaxIdLayer,
SamplingIdLayer.  The reference operates padding-free on
sequenceStartPositions; here every op takes the padded data plus mask/lengths
(see paddle_tpu.core.sequence) and is careful that padding never leaks into
results — the mask-correctness invariant (SURVEY.md §7 hard part (c)).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch

_NEG = -1e30


def seq_max_pool(seq: SequenceBatch):
    """[B, T, D] -> [B, D] max over valid steps (reference MaxLayer)."""
    m = seq.mask()[..., None]
    x = jnp.where(m > 0, seq.data, _NEG)
    out = jnp.max(x, axis=1)
    # all-empty sequences -> 0
    any_valid = (seq.lengths > 0)[:, None]
    return jnp.where(any_valid, out, 0.0)


def seq_avg_pool(seq: SequenceBatch):
    """Average over valid steps (reference AverageLayer, strategy 'average')."""
    m = seq.mask()[..., None]
    s = jnp.sum(seq.data * m, axis=1)
    n = jnp.maximum(seq.lengths.astype(s.dtype), 1.0)[:, None]
    return s / n


def seq_sum_pool(seq: SequenceBatch):
    """Sum over valid steps (reference AverageLayer 'sum' strategy)."""
    return jnp.sum(seq.data * seq.mask()[..., None], axis=1)


def seq_sqrt_pool(seq: SequenceBatch):
    """sum / sqrt(len) (reference AverageLayer 'squarerootn' strategy)."""
    s = seq_sum_pool(seq)
    n = jnp.sqrt(jnp.maximum(seq.lengths.astype(s.dtype), 1.0))[:, None]
    return s / n


def seq_last(seq: SequenceBatch):
    """Last valid step (reference SequenceLastInstanceLayer)."""
    idx = jnp.maximum(seq.lengths - 1, 0)
    return jnp.take_along_axis(
        seq.data, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]


def seq_first(seq: SequenceBatch):
    """First step (reference first_seq / SequenceLastInstanceLayer select_first)."""
    return seq.data[:, 0]


def seq_pool(seq: SequenceBatch, pooling: str):
    return {
        "max": seq_max_pool,
        "avg": seq_avg_pool,
        "average": seq_avg_pool,
        "sum": seq_sum_pool,
        "sqrt": seq_sqrt_pool,
        "last": seq_last,
        "first": seq_first,
    }[pooling](seq)


def expand(vec, like: SequenceBatch):
    """[B, D] -> [B, T, D]: broadcast one row per sequence across its steps
    (reference ExpandLayer)."""
    data = jnp.broadcast_to(vec[:, None, :], (vec.shape[0], like.max_len, vec.shape[-1]))
    data = data * like.mask(vec.dtype)[..., None]
    return SequenceBatch(data=data, lengths=like.lengths)


def seq_concat(a: SequenceBatch, b: SequenceBatch) -> SequenceBatch:
    """Concatenate along time: [a_i ; b_i] per sample (reference
    SequenceConcatLayer).  Output padded to Ta+Tb."""
    bsz, ta = a.data.shape[:2]
    tb = b.data.shape[1]
    tout = ta + tb
    out_len = a.lengths + b.lengths
    # scatter b after a's valid prefix
    pos = jnp.arange(tout, dtype=jnp.int32)[None, :]
    # index into a where pos < len_a, into b where len_a <= pos < len_a+len_b
    in_a = pos < a.lengths[:, None]
    b_idx = jnp.clip(pos - a.lengths[:, None], 0, tb - 1)
    a_idx = jnp.clip(pos, 0, ta - 1)
    ga = jnp.take_along_axis(a.data, a_idx[..., None], axis=1)
    gb = jnp.take_along_axis(b.data, b_idx[..., None], axis=1)
    data = jnp.where(in_a[..., None], ga, gb)
    valid = pos < out_len[:, None]
    return SequenceBatch(data=data * valid[..., None].astype(data.dtype),
                         lengths=out_len)


def seq_reshape(seq: SequenceBatch, new_dim: int) -> SequenceBatch:
    """Re-chunk each sequence's flattened tokens into rows of new_dim
    (reference SequenceReshapeLayer — it reshapes only the VALID ragged
    tokens, so the last row of a sequence whose len*d is not a multiple of
    new_dim is deterministically zero-padded, and the batch's padded length
    must not influence anything)."""
    b, t, d = seq.data.shape
    # zero payload past each sequence's end: without this, garbage in the
    # padding bleeds into the tail output row (padding-invariance sweep)
    data = seq.data * seq.mask(seq.data.dtype)[..., None]
    rows = -(-(t * d) // new_dim)
    flat = data.reshape(b, t * d)
    flat = jnp.pad(flat, ((0, 0), (0, rows * new_dim - t * d)))
    # ceil so a sequence whose len*d is not divisible keeps all its tokens
    new_len = -(-(seq.lengths * d) // new_dim)
    return SequenceBatch(data=flat.reshape(b, rows, new_dim),
                         lengths=new_len.astype(jnp.int32))


def sub_seq(seq: SequenceBatch, offsets, sizes, max_out: int) -> SequenceBatch:
    """Per-sample slice [offset, offset+size) (reference SubSequenceLayer)."""
    pos = jnp.arange(max_out, dtype=jnp.int32)[None, :]
    idx = jnp.clip(offsets[:, None] + pos, 0, seq.max_len - 1)
    data = jnp.take_along_axis(seq.data, idx[..., None], axis=1)
    valid = pos < sizes[:, None]
    return SequenceBatch(data=data * valid[..., None].astype(data.dtype),
                         lengths=sizes.astype(jnp.int32))


def seq_slice(seq: SequenceBatch, starts=None, ends=None) -> SequenceBatch:
    starts = jnp.zeros_like(seq.lengths) if starts is None else starts
    ends = seq.lengths if ends is None else jnp.minimum(ends, seq.lengths)
    return sub_seq(seq, starts, ends - starts, seq.max_len)


def context_projection(seq: SequenceBatch, context_len: int,
                       context_start: int, padding_weights=None):
    """Sliding-window concat over time (reference ContextProjection,
    function/ContextProjectionOp.cpp:392).

    Each step t gets [x_{t+start}, ..., x_{t+start+len-1}] concatenated
    (D*len wide).  Out-of-range positions use zeros, or learned padding rows
    `padding_weights` [pad_rows, D] when trainable padding is configured
    (rows: max(0,-start) heads then tails).
    """
    b, t, d = seq.data.shape
    cols = []
    lengths = seq.lengths
    for k in range(context_len):
        off = context_start + k
        idx = jnp.arange(t, dtype=jnp.int32) + off
        oob_head = idx < 0
        oob_tail = idx[None, :] >= lengths[:, None]
        gathered = seq.data[:, jnp.clip(idx, 0, t - 1), :]
        col = gathered
        if padding_weights is not None:
            n_head = max(0, -context_start)
            if n_head:
                head_row = jnp.clip(idx + n_head, 0, n_head - 1)
                head_pad = padding_weights[jnp.clip(head_row, 0, padding_weights.shape[0] - 1)]
                col = jnp.where(oob_head[None, :, None], head_pad[None], col)
            n_tail = max(0, context_start + context_len - 1)
            if n_tail:
                tail_row = n_head + jnp.clip(idx[None, :] - lengths[:, None], 0, n_tail - 1)
                tail_row = jnp.clip(tail_row, 0, padding_weights.shape[0] - 1)
                tail_pad = padding_weights[tail_row]
                col = jnp.where(oob_tail[..., None], tail_pad, col)
        else:
            oob = oob_head[None, :, None] | oob_tail[..., None]
            col = jnp.where(oob, 0.0, col)
        cols.append(col)
    out = jnp.concatenate(cols, axis=-1)
    return SequenceBatch(data=out * seq.mask(out.dtype)[..., None], lengths=lengths)


def max_id(x):
    """argmax over the feature axis (reference MaxIdLayer)."""
    return jnp.argmax(x, axis=-1).astype(jnp.int32)


def eos_check(ids, eos_id):
    """1.0 where id == eos (reference EosIdCheckLayer)."""
    return (ids == eos_id).astype(jnp.float32)


def sampling_id(rng, probs):
    """Sample an id per row from a prob distribution (reference SamplingIdLayer)."""
    return jax.random.categorical(rng, jnp.log(jnp.maximum(probs, 1e-20)), axis=-1)


def scatter_rows_to_steps(seq: SequenceBatch):
    """[B, T, D] + lengths -> flat [sum_len, D] host-side helper (inverse of
    padding).  Only for eval/IO; not jit-friendly (dynamic shape)."""
    import numpy as np
    data = np.asarray(seq.data)
    lens = np.asarray(seq.lengths)
    return np.concatenate([data[i, :l] for i, l in enumerate(lens)], axis=0)


def seq_strided_pool(seq: SequenceBatch, pooling: str, stride: int
                     ) -> SequenceBatch:
    """last/first over non-overlapping stride windows, producing a SHORTER
    sequence (reference SequenceLastInstanceLayer/SequenceFirstInstanceLayer
    with stride>0, seqlastins config: one instance per window)."""
    b, t = seq.data.shape[:2]
    n_win = -(-t // stride)
    w = jnp.arange(n_win, dtype=jnp.int32)[None, :]               # [1, W]
    if pooling == "first":
        idx = w * stride                                           # [B, W]
        idx = jnp.broadcast_to(idx, (b, n_win))
    elif pooling == "last":
        # last valid element inside each window
        end = jnp.minimum((w + 1) * stride, seq.lengths[:, None])
        idx = jnp.maximum(end - 1, 0)
    else:
        raise ValueError(f"strided seq pool supports last/first, "
                         f"got {pooling!r}")
    gathered = jnp.take_along_axis(
        seq.data, idx.reshape(b, n_win, *([1] * (seq.data.ndim - 2))),
        axis=1)
    out_len = -(-seq.lengths // stride)
    out = SequenceBatch(data=gathered, lengths=out_len.astype(jnp.int32))
    mask = out.mask(gathered.dtype).reshape(
        (b, n_win) + (1,) * (gathered.ndim - 2))
    return SequenceBatch(data=gathered * mask, lengths=out.lengths)


def nested_seq_pool(nested, pooling: str, each_sequence: bool = False):
    """Sequence pooling over a NestedSequenceBatch (reference sequence
    levels, Argument subSequenceStartPositions).

    each_sequence=True (AggregateLevel.TO_SEQUENCE): pool WITHIN each
    sub-sequence -> SequenceBatch [B, S, D] over the outer axis.
    Otherwise pool over ALL valid elements -> [B, D] (last/first pick the
    overall last/first element, matching the flat view of the nested
    data)."""
    from paddle_tpu.core.sequence import NestedSequenceBatch
    assert isinstance(nested, NestedSequenceBatch)
    b, s = nested.data.shape[:2]

    if each_sequence:
        flat = nested.flatten_outer()          # [B*S, T, ...]
        pooled = seq_pool(flat, pooling)       # [B*S, D]
        data = pooled.reshape((b, s) + pooled.shape[1:])
        data = data * nested.outer_mask(data.dtype).reshape(
            (b, s) + (1,) * (data.ndim - 2))
        return SequenceBatch(data=data, lengths=nested.outer_lengths)

    if pooling == "last":
        outer_idx = jnp.maximum(nested.outer_lengths - 1, 0)      # [B]
        per_sub = nested_seq_pool(nested, "last", each_sequence=True)
        return jnp.take_along_axis(
            per_sub.data, outer_idx[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]
    if pooling == "first":
        return nested.data[:, 0, 0]
    # max/avg/sum/sqrt over every valid element: flatten both levels
    flat_data = nested.data.reshape((b, -1) + nested.data.shape[3:])
    mask = nested.inner_mask().reshape(b, -1)
    # reuse the flat kernels via a pseudo SequenceBatch sorted mask? the
    # mask is not a prefix, so compute directly
    m = mask.reshape(mask.shape + (1,) * (flat_data.ndim - 2))
    if pooling == "max":
        out = jnp.max(jnp.where(m > 0, flat_data, _NEG), axis=1)
        return jnp.where((jnp.sum(mask, 1) > 0)[:, None], out, 0.0)
    total = jnp.sum(flat_data * m, axis=1)
    n = jnp.maximum(jnp.sum(mask, axis=1), 1.0)[:, None]
    if pooling in ("avg", "average"):
        return total / n
    if pooling == "sqrt":
        return total / jnp.sqrt(n)
    if pooling == "sum":
        return total
    raise ValueError(f"unsupported nested pooling {pooling!r}")
