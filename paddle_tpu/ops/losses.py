"""Cost/loss ops.

Reference: gserver/layers/CostLayer.{h,cpp} — MSE (square_error), multi-class
cross-entropy (+ soft-dist variant), binary CE over multiple labels, huber
classification/regression, rank cost, lambda-rank, smooth-L1, sum cost — and
the structured/sampled losses live in crf.py / ctc.py / sampling.py.

All take [B, ...] and return a per-sample loss [B]; callers mean over the
batch (the reference sums then divides by num sequences in Argument::sum).
Each is a pure function so autodiff supplies the backward pass.
"""

import jax
import jax.numpy as jnp

_EPS = 1e-10


def square_error(pred, label):
    """MSE (reference CostLayer::SumOfSquaresCostLayer): 0.5*||pred-label||^2."""
    d = pred - label
    return 0.5 * jnp.sum(d * d, axis=-1)


def classification_cost(logits_or_probs, label_ids, *, from_logits=True):
    """Multi-class CE with integer labels (reference MultiClassCrossEntropy)."""
    if from_logits:
        logp = jax.nn.log_softmax(logits_or_probs, axis=-1)
    else:
        logp = jnp.log(jnp.maximum(logits_or_probs, _EPS))
    label_ids = jnp.clip(label_ids.astype(jnp.int32), 0, logp.shape[-1] - 1)
    return -jnp.take_along_axis(logp, label_ids[..., None], axis=-1)[..., 0]


def cross_entropy_with_selfnorm(logits, label_ids, alpha=0.1):
    """Reference MultiClassCrossEntropyWithSelfNorm: CE + alpha*log(Z)^2."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    ce = classification_cost(logits, label_ids)
    return ce + alpha * jnp.square(logz)


def soft_binary_class_cross_entropy(probs, soft_labels):
    """Reference SoftBinaryClassCrossEntropy: sum over dims of binary CE
    against soft targets.  `probs` in (0,1) (apply sigmoid first)."""
    p = jnp.clip(probs, _EPS, 1.0 - _EPS)
    return -jnp.sum(soft_labels * jnp.log(p) + (1 - soft_labels) * jnp.log1p(-p), axis=-1)


def multi_binary_label_cross_entropy(logits, labels):
    """Reference MultiBinaryLabelCrossEntropy: sigmoid CE, multi-hot labels."""
    logp = jax.nn.log_sigmoid(logits)
    lognotp = jax.nn.log_sigmoid(-logits)
    return -jnp.sum(labels * logp + (1 - labels) * lognotp, axis=-1)


def binary_classification_cost(prob, label):
    """Two-class CE on a scalar probability output."""
    p = jnp.clip(prob.reshape(prob.shape[0]), _EPS, 1 - _EPS)
    y = label.reshape(label.shape[0]).astype(p.dtype)
    return -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))


def rank_cost(left, right, label, weight=None):
    """Pairwise rank loss (reference RankingCost):
    C = log(1 + exp(o)) - t*o, o = left - right, t in {0, 0.5, 1}."""
    o = (left - right).reshape(left.shape[0])
    t = label.reshape(label.shape[0]).astype(o.dtype)
    c = jnp.logaddexp(0.0, o) - t * o
    if weight is not None:
        c = c * weight.reshape(weight.shape[0])
    return c


def lambda_cost(scores, relevance, mask, ndcg_num=5):
    """LambdaRank cost over a padded sequence of documents
    (reference LambdaCost, gserver/layers/CostLayer.cpp).

    scores, relevance, mask: [B, T].  Returns a [B] surrogate whose gradient
    matches the lambda gradients: for each pair (i, j) with rel_i > rel_j the
    score difference is pushed by |delta NDCG|.  We compute the standard
    LambdaRank pairwise logistic with |ΔNDCG| weights, stopping gradients
    through the weights.
    """
    s_i = scores[:, :, None]
    s_j = scores[:, None, :]
    r_i = relevance[:, :, None]
    r_j = relevance[:, None, :]
    valid = (mask[:, :, None] * mask[:, None, :]) > 0
    pair = (r_i > r_j) & valid

    # ideal DCG per list (top-ndcg_num), for NDCG normalization
    topk = jnp.sort(jnp.where(mask > 0, relevance, -jnp.inf), axis=-1)[:, ::-1]
    k = min(ndcg_num, scores.shape[-1])
    disc = 1.0 / jnp.log2(jnp.arange(2, k + 2).astype(scores.dtype))
    ideal = jnp.sum(jnp.where(jnp.isfinite(topk[:, :k]),
                              (2.0 ** topk[:, :k] - 1) * disc, 0.0), axis=-1)
    ideal = jnp.maximum(ideal, _EPS)[:, None, None]

    # rank positions by current scores
    order = jnp.argsort(jnp.argsort(
        jnp.where(mask > 0, -scores, jnp.inf), axis=-1), axis=-1)  # 0 = best
    d = 1.0 / jnp.log2(2.0 + order.astype(scores.dtype))
    gain = 2.0 ** relevance - 1.0
    delta_ndcg = jnp.abs(
        (gain[:, :, None] - gain[:, None, :]) *
        (d[:, :, None] - d[:, None, :])) / ideal
    w = jax.lax.stop_gradient(jnp.where(pair, delta_ndcg, 0.0))
    loss = w * jnp.logaddexp(0.0, -(s_i - s_j))
    return jnp.sum(loss, axis=(1, 2))


def huber_regression(pred, label, delta=1.0):
    d = jnp.abs(pred - label)
    return jnp.sum(jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta)), axis=-1)


def huber_classification(pred, label):
    """Reference HuberTwoClassification: labels {0,1} -> y in {-1,1}."""
    y = (2.0 * label.reshape(label.shape[0]) - 1.0).astype(pred.dtype)
    a = y * pred.reshape(pred.shape[0])
    return jnp.where(a < -1.0, -4.0 * a, jnp.where(a < 1.0, jnp.square(1.0 - a), 0.0))


def smooth_l1(pred, label):
    d = pred - label
    ad = jnp.abs(d)
    return jnp.sum(jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5), axis=-1)


def sum_cost(x):
    """Reference SumCostLayer: just sums the input."""
    return jnp.sum(x, axis=tuple(range(1, x.ndim)))


def masked_seq_mean(per_token_loss, mask):
    """Average a [B, T] per-token loss over valid tokens, per sample."""
    tot = jnp.sum(per_token_loss * mask, axis=-1)
    return tot / jnp.maximum(jnp.sum(mask, axis=-1), 1.0)
