"""Batch normalization.

Reference: BatchNormBaseLayer / BatchNormalizationLayer / CudnnBatchNorm
(gserver/layers/BatchNorm*.cpp) with use_global_stats switching and
moving-average accumulation.  Functional form: apply returns (y, new_state)
so the moving stats thread through the training step as explicit state —
no mutation, jit-friendly.
"""

import jax.numpy as jnp


def batch_norm_train(x, gamma, beta, moving_mean, moving_var,
                     momentum=0.9, eps=1e-5, axis=None):
    """Normalize over all axes except the channel (last) axis.

    Returns (y, (new_moving_mean, new_moving_var)).
    """
    if axis is None:
        axis = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=axis)
    var = jnp.var(x, axis=axis)
    y = (x - mean) / jnp.sqrt(var + eps)
    y = y * gamma + beta
    new_mean = momentum * moving_mean + (1.0 - momentum) * mean
    new_var = momentum * moving_var + (1.0 - momentum) * var
    return y, (new_mean, new_var)


def batch_norm_infer(x, gamma, beta, moving_mean, moving_var, eps=1e-5):
    y = (x - moving_mean) / jnp.sqrt(moving_var + eps)
    return y * gamma + beta


def layer_norm(x, gamma, beta, eps=1e-6):
    """LayerNorm (new capability for the transformer stack)."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * gamma + beta


def rms_norm(x, gamma, eps=1e-6):
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x / jnp.sqrt(ms + eps) * gamma
