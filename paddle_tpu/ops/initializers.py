"""Parameter initializers.

The reference's default is gaussian std = 1/sqrt(fan_in) per ParameterConfig
(reference: python/paddle/trainer/config_parser.py Parameter() defaults,
parameter/Parameter.cpp randomize()).  Exposed here as first-class
initializer fns (rng, shape, dtype) -> array.
"""

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes


def _dtype(dtype):
    return dtype or dtypes.param_dtype()


def constant(value=0.0):
    def init(rng, shape, dtype=None):
        return jnp.full(shape, value, dtype=_dtype(dtype))
    return init


def normal(std=None, mean=0.0):
    """std=None -> reference default 1/sqrt(fan_in) (fan_in = shape[0])."""
    def init(rng, shape, dtype=None):
        s = std if std is not None else 1.0 / math.sqrt(max(shape[0], 1))
        return mean + s * jax.random.normal(rng, shape, dtype=_dtype(dtype))
    return init


def uniform(scale=None):
    def init(rng, shape, dtype=None):
        s = scale if scale is not None else 1.0 / math.sqrt(max(shape[0], 1))
        return jax.random.uniform(rng, shape, dtype=_dtype(dtype), minval=-s, maxval=s)
    return init


def xavier():
    def init(rng, shape, dtype=None):
        fan_in = shape[0] if len(shape) >= 1 else 1
        fan_out = shape[-1] if len(shape) >= 2 else fan_in
        s = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype=_dtype(dtype), minval=-s, maxval=s)
    return init


def msra(fan_in_axis=0):
    def init(rng, shape, dtype=None):
        fan_in = shape[fan_in_axis] if shape else 1
        s = math.sqrt(2.0 / max(fan_in, 1))
        return s * jax.random.normal(rng, shape, dtype=_dtype(dtype))
    return init


def conv_default():
    """Reference conv init: normal with std 1/sqrt(fan_in), fan_in = prod(kernel)*in_ch."""
    def init(rng, shape, dtype=None):
        # shape: [kh, kw, in_ch, out_ch]
        fan_in = 1
        for d in shape[:-1]:
            fan_in *= d
        s = 1.0 / math.sqrt(max(fan_in, 1))
        return s * jax.random.normal(rng, shape, dtype=_dtype(dtype))
    return init
