"""Sparse-row embedding machinery (the large-vocab path).

Reference: math/SparseRowMatrix.h:204 (rows touched this batch gathered into
a dense buffer, updated, scattered back), trainer/RemoteParameterUpdater.h:265
(sparse push/pull of touched rows only), parameter/FirstOrderOptimizer
sparse update hooks.

TPU-native design: the touched-id set is computed with `jnp.unique(size=K)`
— a STATIC row budget keeps shapes XLA-compilable — and the train step
differentiates with respect to the GATHERED [K, D] row block instead of the
[V, D] table, so the gradient, the optimizer math, and the scatter-back all
cost O(K·D) regardless of vocab size.  Unused budget slots get index == vocab
and are dropped by out-of-bounds scatter (`mode='drop'`), so no dummy row or
masking pass is needed.
"""

import jax.numpy as jnp


def default_row_budget(n_ids):
    """Static unique-row budget for a batch of n_ids tokens (next power of
    two, capped at n_ids: a batch can't touch more rows than it has ids)."""
    b = 1
    while b < n_ids:
        b *= 2
    return b


def unique_touched(ids, budget, vocab):
    """ids: int array (any shape) -> (uids [budget], inv ids.shape).

    uids lists the distinct ids touched this batch; slots beyond the actual
    unique count hold `vocab` (out of range on purpose).  inv re-expresses
    ids as positions into uids, so `rows[inv]` == `table[ids]` after
    `rows = gather_rows(table, uids)`.  If the batch touches more than
    `budget` distinct ids, jnp.unique truncates — pick the budget >= the
    worst-case distinct count (`default_row_budget(ids.size)` is always
    safe)."""
    flat = ids.reshape(-1).astype(jnp.int32)
    uids, inv = jnp.unique(flat, return_inverse=True, size=budget,
                           fill_value=vocab)
    return uids, inv.reshape(ids.shape).astype(jnp.int32)


def gather_rows(table, uids):
    """[V, D] x [K] -> [K, D]; out-of-range uids (the fill slots) clip to the
    last row — their values are never consumed and their updates are dropped
    by scatter_rows."""
    return table[jnp.clip(uids, 0, table.shape[0] - 1)]


def scatter_rows(table, uids, new_rows):
    """Write updated rows back; fill-slot indices (== vocab) fall out of
    bounds and are DROPPED, touching nothing."""
    return table.at[uids].set(new_rows, mode="drop")
