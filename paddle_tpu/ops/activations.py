"""Activation registry.

Reference: paddle/gserver/activations/ActivationFunction.cpp:94-405 registers
13 activations: sigmoid, softmax, sequence_softmax, relu, brelu, tanh, stanh,
softrelu, abs, square, exponential, log (+ linear/identity).  Hand-written
backward passes there are replaced by autodiff here.
"""

import jax
import jax.numpy as jnp

_REGISTRY = {}


def register(name):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def get(name):
    if callable(name):
        return name
    if name in (None, "", "linear", "identity"):
        return lambda x: x
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown activation {name!r}; have {sorted(_REGISTRY)}")


def names():
    return sorted(_REGISTRY)


register("sigmoid")(jax.nn.sigmoid)
register("relu")(jax.nn.relu)
register("tanh")(jnp.tanh)
register("abs")(jnp.abs)
register("square")(jnp.square)
register("exponential")(jnp.exp)
register("sqrt")(lambda x: jnp.sqrt(jnp.maximum(x, 0.0)))


@register("softmax")
def softmax(x):
    return jax.nn.softmax(x, axis=-1)


@register("log")
def log(x):
    return jnp.log(jnp.maximum(x, 1e-20))


@register("brelu")
def brelu(x):
    # reference BReluActivation: min(max(x, 0), 24)
    return jnp.clip(x, 0.0, 24.0)


@register("softrelu")
def softrelu(x):
    # reference SoftReluActivation: log(1 + exp(clip(x, -40, 40)))
    return jnp.log1p(jnp.exp(jnp.clip(x, -40.0, 40.0)))


@register("stanh")
def stanh(x):
    # reference STanhActivation: 1.7159 * tanh(2/3 x)
    return 1.7159 * jnp.tanh((2.0 / 3.0) * x)


def sequence_softmax(x, mask):
    """Softmax over the time axis of a padded [B, T] (or [B, T, 1]) batch.

    Reference SequenceSoftmaxActivation normalizes within each ragged
    sequence; here padding is masked out before the softmax.
    """
    squeeze = False
    if x.ndim == 3 and x.shape[-1] == 1:
        x = x[..., 0]
        squeeze = True
    x = jnp.where(mask > 0, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=-1)
    out = jnp.where(mask > 0, out, 0.0)
    if squeeze:
        out = out[..., None]
    return out
