"""Recurrent ops: fused LSTM/GRU cells + whole-sequence scans + the generic
recurrent-group engine.

Reference: LstmLayer/LstmCompute + hl_lstm fused kernels
(cuda/include/hl_lstm_ops.cuh:46-66: gate order [input, input_gate,
forget_gate, output_gate], peephole checkI/F/O), GatedRecurrentLayer /
GruCompute (cuda/include/hl_gru_ops.cuh:37-80: h = prev - u*prev + u*c),
RecurrentLayer, and the per-step unrolled engine
RecurrentGradientMachine.cpp:379-712.

TPU design: whole-sequence compute is one `lax.scan` whose body is a fused
(gate-matmul + elementwise) step — XLA fuses the elementwise block; the
input-to-hidden projection for ALL timesteps is hoisted out of the scan as a
single big MXU matmul (the same trick as the reference's SequenceToBatch
batching, but in time-major form).  Padding is handled by carrying state
through masked steps unchanged, so results match the reference's padding-free
semantics exactly.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import NestedSequenceBatch, SequenceBatch
from paddle_tpu.ops import activations
from paddle_tpu.ops.linear import matmul


class LstmState(NamedTuple):
    h: jnp.ndarray  # [B, D] hidden (output)
    c: jnp.ndarray  # [B, D] cell state


def lstm_cell(x4, state: LstmState, w_r, check_i=None, check_f=None,
              check_o=None, act="tanh", gate_act="sigmoid", state_act="tanh"):
    """One LSTM step.

    x4: [B, 4D] input already projected to the 4 gates in reference order
        [in, input_gate, forget_gate, output_gate] (hl_lstm_ops.cuh:46).
    w_r: [D, 4D] recurrent weights.  check_*: [D] peepholes (optional).
    """
    d = state.h.shape[-1]
    gates = x4 + matmul(state.h, w_r)
    a, ig, fg, og = jnp.split(gates, 4, axis=-1)
    act_f = activations.get(act)
    gate_f = activations.get(gate_act)
    state_f = activations.get(state_act)
    a = act_f(a)
    if check_i is not None:
        ig = ig + state.c * check_i
    if check_f is not None:
        fg = fg + state.c * check_f
    i = gate_f(ig)
    f = gate_f(fg)
    c = a * i + state.c * f
    if check_o is not None:
        og = og + c * check_o
    o = gate_f(og)
    h = o * state_f(c)
    return LstmState(h=h, c=c)


def gru_cell(x3, h_prev, w_gate, w_state, act="tanh", gate_act="sigmoid"):
    """One GRU step (reference hl_gru_ops.cuh:37-80).

    x3: [B, 3D] projected input, layout [update, reset, candidate].
    w_gate: [D, 2D] recurrent weights for update/reset;
    w_state: [D, D] recurrent weights for the candidate.
    h = prev - u*prev + u*c~,  c~ = act(x_c + (r*prev) @ w_state)
    """
    d = h_prev.shape[-1]
    xu, xr, xc = x3[..., :d], x3[..., d:2 * d], x3[..., 2 * d:]
    ru = matmul(h_prev, w_gate)
    gate_f = activations.get(gate_act)
    u = gate_f(xu + ru[..., :d])
    r = gate_f(xr + ru[..., d:])
    c = activations.get(act)(xc + matmul(r * h_prev, w_state))
    return h_prev - u * h_prev + u * c


def simple_rnn_cell(x, h_prev, w_r, act="tanh"):
    """Reference RecurrentLayer: h = act(x + h_prev @ w_r)."""
    return activations.get(act)(x + matmul(h_prev, w_r))


# lax.scan unroll factor for the sequence loops: >1 lets XLA pipeline
# consecutive steps (fewer loop-carried syncs on the TPU scalar core) at the
# cost of compile time.  Overridable via PADDLE_TPU_SCAN_UNROLL.
import os as _os

SCAN_UNROLL = int(_os.environ.get("PADDLE_TPU_SCAN_UNROLL", "1"))

# Fused whole-sequence Pallas RNN kernels (ops/pallas/{lstm,gru,
# simple_rnn}.py): weights + state stay VMEM-resident across the time loop
# instead of round-tripping HBM every scan step.  Gates ALL THREE kernels.
# Values: "auto" (default; kernels on real TPU, scan elsewhere — interpret
# mode is slower than the scan and only useful for testing), "always"
# (kernels everywhere, interpret off-TPU), "0"/"off" (scan everywhere);
# "1" is a legacy alias for auto.
# PADDLE_TPU_FUSED_RNN is the primary env var; PADDLE_TPU_FUSED_LSTM is an
# accepted alias from before the GRU kernel existed.
FUSED_LSTM = _os.environ.get(
    "PADDLE_TPU_FUSED_RNN",
    _os.environ.get("PADDLE_TPU_FUSED_LSTM", "auto"))


def _fused_lstm_enabled():
    if FUSED_LSTM == "always":
        return True
    if FUSED_LSTM in ("0", "off", "false", "no"):
        return False
    # "1" keeps its legacy meaning: enabled-with-auto-gating (kernel on
    # real TPU only) — NOT force-on, which would switch CPU boxes to the
    # slow interpret path
    if FUSED_LSTM not in ("auto", "1", ""):
        from paddle_tpu.utils.logging import logger
        logger.warning("PADDLE_TPU_FUSED_RNN=%r not recognized "
                       "(auto|always|0); treating as auto", FUSED_LSTM)
    return jax.default_backend() == "tpu"


#: incremented on every fused-kernel dispatch (trace time).  Observers
#: (bench.py) snapshot it around a compile to learn whether the fused path
#: was ACTUALLY taken for a given model/shape — the one source of truth,
#: instead of re-deriving supported()'s decision externally.
FUSED_DISPATCH_COUNT = 0


def _fused_seq_apply(seq, xs, ms, reverse, kernel_fn):
    """Shared fused-kernel dispatch: reverse = forward kernel over
    time-flipped arrays, flipped back (valid because sequences are
    left-aligned; masked steps freeze the carry identically either way).
    Returns (SequenceBatch, final-state) from kernel_fn(xs_tm, ms_tm)."""
    global FUSED_DISPATCH_COUNT
    FUSED_DISPATCH_COUNT += 1
    xs_k = jnp.flip(xs, 0) if reverse else xs
    ms_k = jnp.flip(ms, 0) if reverse else ms
    hs_tm, final = kernel_fn(xs_k, ms_k)
    if reverse:
        hs_tm = jnp.flip(hs_tm, 0)
    out = hs_tm.transpose(1, 0, 2) * seq.mask(hs_tm.dtype)[..., None]
    return SequenceBatch(data=out, lengths=seq.lengths), final


def _masked_scan(step, init_carry, xs_time_major, mask_time_major, reverse=False):
    """Scan over time; where mask==0 the carry passes through unchanged."""
    def body(carry, inp):
        x, m = inp
        new_carry = step(carry, x)
        merged = jax.tree_util.tree_map(
            lambda new, old: jnp.where(
                m.reshape((-1,) + (1,) * (new.ndim - 1)) > 0, new, old),
            new_carry, carry)
        return merged, merged
    return jax.lax.scan(body, init_carry, (xs_time_major, mask_time_major),
                        reverse=reverse, unroll=SCAN_UNROLL)


def lstm(seq: SequenceBatch, w_r, bias=None, check_i=None, check_f=None,
         check_o=None, reverse=False, act="tanh", gate_act="sigmoid",
         state_act="tanh", init_state=None):
    """Whole-sequence LSTM (reference LstmLayer + SequenceToBatch).

    seq.data: [B, T, 4D] pre-projected gate inputs (the reference's lstmemory
    also expects a 4*size mixed input).  bias: [4D].  Returns
    (SequenceBatch of h [B, T, D], final LstmState).
    """
    b, t, d4 = seq.data.shape
    d = d4 // 4
    x = seq.data if bias is None else seq.data + bias
    xs = x.transpose(1, 0, 2)                       # time-major [T, B, 4D]
    ms = seq.mask().transpose(1, 0)                 # [T, B]

    if _fused_lstm_enabled():
        # import inside the branch: a broken pallas install must not take
        # the scan fallback down with it
        from paddle_tpu.ops.pallas import lstm as pl_lstm
        from paddle_tpu.ops.pallas import lstm_blocked as pl_lstm_blk
        if pl_lstm.supported(b, d, act, gate_act, state_act, init_state):
            sb, (fh, fc) = _fused_seq_apply(
                seq, xs, ms, reverse,
                lambda x, m: pl_lstm.lstm_fused(x, m, w_r, check_i,
                                                check_f, check_o))
            return sb, LstmState(h=fh, c=fc)
        # over-VMEM hidden sizes: the gate-blocked forward keeps the carry
        # in VMEM and fuses the cell while streaming weight blocks (scan-
        # equivalent weight traffic; docs/kernels.md blocked-variant notes)
        if pl_lstm_blk.supported(b, d, act, gate_act, state_act,
                                 init_state):
            sb, (fh, fc) = _fused_seq_apply(
                seq, xs, ms, reverse,
                lambda x, m: pl_lstm_blk.lstm_fused_blocked(
                    x, m, w_r, check_i, check_f, check_o))
            return sb, LstmState(h=fh, c=fc)

    if init_state is None:
        init_state = LstmState(h=jnp.zeros((b, d), x.dtype),
                               c=jnp.zeros((b, d), x.dtype))

    def step(state, x4):
        return lstm_cell(x4, state, w_r, check_i, check_f, check_o,
                         act, gate_act, state_act)

    final, hs = _masked_scan(step, init_state, xs, ms, reverse=reverse)
    out = hs.h.transpose(1, 0, 2) * seq.mask(hs.h.dtype)[..., None]
    return SequenceBatch(data=out, lengths=seq.lengths), final


def gru(seq: SequenceBatch, w_gate, w_state, bias=None, reverse=False,
        act="tanh", gate_act="sigmoid", init_state=None):
    """Whole-sequence GRU (reference GatedRecurrentLayer).

    seq.data: [B, T, 3D] pre-projected [update|reset|candidate] inputs.
    """
    b, t, d3 = seq.data.shape
    d = d3 // 3
    x = seq.data if bias is None else seq.data + bias
    xs = x.transpose(1, 0, 2)
    ms = seq.mask().transpose(1, 0)

    if _fused_lstm_enabled():
        from paddle_tpu.ops.pallas import gru as pl_gru
        if pl_gru.supported(b, d, act, gate_act, init_state):
            return _fused_seq_apply(
                seq, xs, ms, reverse,
                lambda x, m: pl_gru.gru_fused(x, m, w_gate, w_state))

    if init_state is None:
        init_state = jnp.zeros((b, d), x.dtype)

    def step(h, x3):
        return gru_cell(x3, h, w_gate, w_state, act, gate_act)

    final, hs = _masked_scan(step, init_state, xs, ms, reverse=reverse)
    out = hs.transpose(1, 0, 2) * seq.mask(hs.dtype)[..., None]
    return SequenceBatch(data=out, lengths=seq.lengths), final


def simple_rnn(seq: SequenceBatch, w_r, bias=None, reverse=False, act="tanh",
               init_state=None):
    """Reference RecurrentLayer over a whole sequence; input pre-projected [B,T,D]."""
    b, t, d = seq.data.shape
    x = seq.data if bias is None else seq.data + bias
    xs = x.transpose(1, 0, 2)
    ms = seq.mask().transpose(1, 0)

    if _fused_lstm_enabled():
        from paddle_tpu.ops.pallas import simple_rnn as pl_rnn
        if pl_rnn.supported(b, d, act, init_state):
            return _fused_seq_apply(
                seq, xs, ms, reverse,
                lambda x, m: pl_rnn.simple_rnn_fused(x, m, w_r))

    if init_state is None:
        init_state = jnp.zeros((b, d), x.dtype)
    final, hs = _masked_scan(lambda h, xt: simple_rnn_cell(xt, h, w_r, act),
                             init_state, xs, ms, reverse=reverse)
    out = hs.transpose(1, 0, 2) * seq.mask(hs.dtype)[..., None]
    return SequenceBatch(data=out, lengths=seq.lengths), final


def recurrent_group(step_fn, inputs, boot_memories, reverse=False, rng=None):
    """The generic dynamic-RNN engine (reference RecurrentGradientMachine
    forward :379 / createInFrameInfo :642).

    step_fn(memories, frame_inputs) -> (new_memories, frame_outputs), where
    `memories` is any pytree of [B, ...] arrays (the reference's memory()
    links with boot layers) and frame_inputs is a pytree of per-step slices.
    With rng= given, step_fn is called as step_fn(memories, frame_inputs,
    step_rng) where step_rng is an INDEPENDENT key per timestep (so dropout
    masks inside the step decorrelate across time).

    inputs: pytree of SequenceBatch sharing lengths; scanned time-major.
    Returns (pytree of SequenceBatch outputs, final memories).

    The reference shrinks the batch as short sequences finish (dynamic
    shapes); here finished sequences' memories are frozen by masking, which
    is numerically identical and keeps shapes static for XLA.
    """
    leaves = jax.tree_util.tree_leaves(inputs, is_leaf=lambda x: isinstance(x, SequenceBatch))
    ref = leaves[0]
    mask_tm = ref.mask().transpose(1, 0)

    xs_tm = jax.tree_util.tree_map(
        lambda sb: sb.data.transpose((1, 0) + tuple(range(2, sb.data.ndim))),
        inputs, is_leaf=lambda x: isinstance(x, SequenceBatch))

    def merge(mem, new_mem, m):
        return jax.tree_util.tree_map(
            lambda new, old: jnp.where(
                m.reshape((-1,) + (1,) * (new.ndim - 1)) > 0, new, old),
            new_mem, mem)

    if rng is not None:
        keys_tm = jax.random.split(rng, ref.data.shape[1])   # [T, 2]

        def body(mem, scanned):
            x, m, k = scanned
            new_mem, out = step_fn(mem, x, k)
            return merge(mem, new_mem, m), out

        final_mem, outs_tm = jax.lax.scan(
            body, boot_memories, (xs_tm, mask_tm, keys_tm), reverse=reverse,
            unroll=SCAN_UNROLL)
    else:
        def body(mem, scanned):
            x, m = scanned
            new_mem, out = step_fn(mem, x)
            return merge(mem, new_mem, m), out

        final_mem, outs_tm = jax.lax.scan(
            body, boot_memories, (xs_tm, mask_tm), reverse=reverse,
            unroll=SCAN_UNROLL)
    outs = jax.tree_util.tree_map(
        lambda o: SequenceBatch(
            data=o.transpose((1, 0) + tuple(range(2, o.ndim)))
            * ref.mask(o.dtype).reshape(ref.mask().shape + (1,) * (o.ndim - 2)),
            lengths=ref.lengths),
        outs_tm)
    return outs, final_mem


def nested_recurrent_group(step_fn, inputs, boot_memories, reverse=False,
                           rng=None):
    """Two-level (sub-sequence) recurrent engine: the OUTER scan iterates
    subsequences (reference RecurrentGradientMachine createInFrameInfo with
    subsequence inputs, RecurrentGradientMachine.cpp:642-712); at outer step
    j, step_fn sees each input's j-th subsequence as a whole SequenceBatch —
    an inner recurrent_group inside the step scans it as usual, so the pair
    compiles to a nested lax.scan with fully static shapes.

    step_fn(memories, frames[, step_rng]) -> (new_memories, outputs) where
    frames is a tuple of SequenceBatch (one per NestedSequenceBatch input).
    Outer memories are [B, ...] arrays frozen at padded outer steps (the
    masking equivalent of the reference's batch shrinking).

    Step outputs that are [B, ...] arrays stack into a SequenceBatch over the
    outer axis (one row per subsequence); step outputs that are themselves
    SequenceBatch stack into a NestedSequenceBatch — the reference's
    seq-level-output-in-nested-group semantics.
    """
    inputs = tuple(inputs)
    ref = inputs[0]
    outer_mask_sm = ref.outer_mask().transpose(1, 0)          # [S, B]
    datas_sm = tuple(
        n.data.transpose((1, 0) + tuple(range(2, n.data.ndim)))
        for n in inputs)                                       # each [S, B, T, ...]
    ilens_sm = tuple(n.inner_lengths.transpose(1, 0) for n in inputs)

    def merge(mem, new_mem, m):
        return jax.tree_util.tree_map(
            lambda new, old: jnp.where(
                m.reshape((-1,) + (1,) * (new.ndim - 1)) > 0, new, old),
            new_mem, mem)

    def body(mem, scanned):
        if rng is not None:
            datas, ilens, m, k = scanned
            frames = tuple(SequenceBatch(data=d, lengths=l)
                           for d, l in zip(datas, ilens))
            new_mem, out = step_fn(mem, frames, k)
        else:
            datas, ilens, m = scanned
            frames = tuple(SequenceBatch(data=d, lengths=l)
                           for d, l in zip(datas, ilens))
            new_mem, out = step_fn(mem, frames)
        return merge(mem, new_mem, m), out

    S = ref.data.shape[1]
    if rng is not None:
        xs = (datas_sm, ilens_sm, outer_mask_sm, jax.random.split(rng, S))
    else:
        xs = (datas_sm, ilens_sm, outer_mask_sm)
    final_mem, outs_sm = jax.lax.scan(body, boot_memories, xs,
                                      reverse=reverse)

    omask = ref.outer_mask()                                   # [B, S]

    def collect(o):
        # after scan-stacking, a per-step SequenceBatch output has fields
        # data [S, B, T, ...], lengths [S, B]
        if isinstance(o, SequenceBatch):
            data = o.data.transpose((1, 0) + tuple(range(2, o.data.ndim)))
            inner = (o.lengths.transpose(1, 0)
                     * ref.outer_mask(o.lengths.dtype))
            nsb = NestedSequenceBatch(data=data,
                                      outer_lengths=ref.outer_lengths,
                                      inner_lengths=inner)
            return NestedSequenceBatch(
                data=data * nsb.inner_mask(data.dtype).reshape(
                    nsb.inner_mask().shape + (1,) * (data.ndim - 3)),
                outer_lengths=ref.outer_lengths, inner_lengths=inner)
        data = o.transpose((1, 0) + tuple(range(2, o.ndim)))   # [B, S, ...]
        data = data * omask.astype(data.dtype).reshape(
            omask.shape + (1,) * (data.ndim - 2))
        return SequenceBatch(data=data, lengths=ref.outer_lengths)

    outs = jax.tree_util.tree_map(
        collect, outs_sm, is_leaf=lambda x: isinstance(x, SequenceBatch))
    return outs, final_mem


def bidirectional(fwd_out: SequenceBatch, bwd_out: SequenceBatch) -> SequenceBatch:
    """Concat forward and reverse passes (reference bidirectional_lstm)."""
    return SequenceBatch(
        data=jnp.concatenate([fwd_out.data, bwd_out.data], axis=-1),
        lengths=fwd_out.lengths)


# ------------------------------------------------- multi-dimensional LSTM

def md_lstm_2d(x5, w_r_row, w_r_col, check_i_row=None, check_i_col=None,
               check_f_row=None, check_f_col=None, check_o=None,
               act="tanh", gate_act="sigmoid", state_act="tanh"):
    """2-D multi-dimensional LSTM (reference MDLstmLayer.cpp:158-178,
    REGISTER_LAYER(mdlstmemory)): each cell sees two predecessors (top and
    left), each with its own forget gate and recurrent weights:

      state = actIn(a)*actGate(ig) + sum_j actGate(fg_j)*state_prev_j
      gates = x5 + sum_j h_prev_j @ w_r_j (+ peepholes)

    x5: [B, H, W, 5*D] pre-projected (a, ig, fg_row, fg_col, og — the
    reference's size*(3+numDims) IG layout for numDims=2).
    w_r_row/w_r_col: [D, 5*D] recurrent weights for the top/left neighbor.

    TPU mapping: scan over rows carrying the previous row's (h, c)
    [B, W, D]; the inner column scan carries (h_left, c_left).  XLA
    unrolls both into static-shape loops (no dynamic control flow).
    """
    b, h, w, d5 = x5.shape
    d = d5 // 5
    act_f, gate_f, state_f = (activations.get(act), activations.get(gate_act),
                              activations.get(state_act))
    zeros_bd = jnp.zeros((b, d), x5.dtype)

    def cell(x, h_top, c_top, h_left, c_left):
        gates = (x + matmul(h_top, w_r_row) + matmul(h_left, w_r_col))
        a, ig, fg_r, fg_c, og = jnp.split(gates, 5, axis=-1)
        if check_i_row is not None:
            ig = ig + c_top * check_i_row
        if check_i_col is not None:
            ig = ig + c_left * check_i_col
        if check_f_row is not None:
            fg_r = fg_r + c_top * check_f_row
        if check_f_col is not None:
            fg_c = fg_c + c_left * check_f_col
        c = (act_f(a) * gate_f(ig) + gate_f(fg_r) * c_top
             + gate_f(fg_c) * c_left)
        if check_o is not None:
            og = og + c * check_o
        hh = gate_f(og) * state_f(c)
        return hh, c

    def row_step(prev_row, x_row):
        # prev_row: (h_top [B, W, D], c_top [B, W, D]); x_row: [B, W, 5D]
        h_top, c_top = prev_row

        def col_step(carry, inp):
            h_left, c_left = carry
            x, ht, ct = inp
            hh, cc = cell(x, ht, ct, h_left, c_left)
            return (hh, cc), (hh, cc)

        xs = (x_row.transpose(1, 0, 2), h_top.transpose(1, 0, 2),
              c_top.transpose(1, 0, 2))
        _, (hs, cs) = jax.lax.scan(col_step, (zeros_bd, zeros_bd), xs)
        h_row = hs.transpose(1, 0, 2)       # [B, W, D]
        c_row = cs.transpose(1, 0, 2)
        return (h_row, c_row), h_row

    zeros_row = jnp.zeros((b, w, d), x5.dtype)
    _, out = jax.lax.scan(row_step, (zeros_row, zeros_row),
                          x5.transpose(1, 0, 2, 3))
    return out.transpose(1, 0, 2, 3)        # [B, H, W, D]
