"""Mixture-of-experts FFN with expert parallelism.

A post-reference capability (the reference predates MoE) backing the mesh's
'expert' axis (parallel/mesh.py AXIS_EXPERT).  TPU-first shape: experts are
one batched [E, D, F] einsum, so sharding the E dim over the 'expert' axis
makes every device compute ONLY its local experts over all tokens and XLA
inserts the psum that combines partial expert outputs — expert parallelism
derived from shardings, no hand-written all-to-all.  Gating is dense
top-k with renormalization (Switch/GShard style): no dynamic shapes, no
scatter — everything stays MXU-friendly einsums under jit.
"""

import jax
import jax.numpy as jnp


def init_moe(rng, d_model, d_ff, n_experts, dtype=jnp.float32):
    kg, k1, k2 = jax.random.split(rng, 3)
    scale = d_model ** -0.5
    return {
        "wg": (jax.random.normal(kg, (d_model, n_experts)) * scale
               ).astype(dtype),
        "w1": (jax.random.normal(k1, (n_experts, d_model, d_ff)) * scale
               ).astype(dtype),
        "w2": (jax.random.normal(k2, (n_experts, d_ff, d_model))
               * d_ff ** -0.5).astype(dtype),
    }


def router_probs(x, wg):
    """Softmax router probabilities: x [..., D], wg [D, E] -> [..., E]."""
    return jax.nn.softmax(x @ wg, axis=-1)


def moe_gates(probs, top_k):
    """Top-k gates from router probs, renormalized over the kept experts;
    EXACTLY top_k experts stay nonzero even on tied probabilities (index-
    based mask, not a >=threshold)."""
    e = probs.shape[-1]
    if top_k >= e:
        return probs
    _, idx = jax.lax.top_k(probs, top_k)            # [..., top_k]
    mask = jax.nn.one_hot(idx, e, dtype=probs.dtype).sum(-2)
    kept = probs * mask
    return kept / jnp.maximum(kept.sum(-1, keepdims=True), 1e-9)


def aux_load_balance_loss(probs, gates, top_k, valid=None):
    """GShard/Switch auxiliary loss over precomputed router tensors:
    E * sum_e(frac_tokens_picking_e * mean_prob_e); minimized (=1) at
    uniform expert utilization.  valid: optional [...] token mask — the
    statistics count REAL tokens only, so padding (which routes
    identically everywhere) can't skew the balance pressure."""
    e = probs.shape[-1]
    picked = (gates > 0).astype(probs.dtype)
    if valid is None:
        frac = picked.reshape(-1, e).mean(0) / max(top_k, 1)
        mean_prob = probs.reshape(-1, e).mean(0)
    else:
        w = valid.astype(probs.dtype).reshape(-1, 1)
        n = jnp.maximum(w.sum(), 1.0)
        frac = (picked.reshape(-1, e) * w).sum(0) / n / max(top_k, 1)
        mean_prob = (probs.reshape(-1, e) * w).sum(0) / n
    return e * jnp.sum(frac * mean_prob)


def moe_ffn(x, params, top_k=2, act=jax.nn.gelu, return_aux=False,
            valid=None):
    """x: [B, T, D] -> [B, T, D] through E gated FFN experts.

    All experts run as one batched einsum over the E dim; under a mesh with
    w1/w2 sharded P('expert', ...) each device computes its local experts'
    partial output and the gate-weighted combine psums across the axis.
    The router runs ONCE; return_aux=True additionally returns the
    load-balance loss built from the same probs/gates, restricted to
    `valid` [B, T] tokens when given (padding must not train the
    router)."""
    probs = router_probs(x, params["wg"])              # [B, T, E]
    gates = moe_gates(probs, top_k)
    h = act(jnp.einsum("btd,edf->btef", x, params["w1"]))
    y = jnp.einsum("btef,efd->bted", h, params["w2"])
    out = jnp.einsum("bted,bte->btd", y, gates)
    if return_aux:
        return out, aux_load_balance_loss(probs, gates, top_k, valid)
    return out


def expert_shardings(mesh, axis="expert"):
    """NamedShardings for an init_moe params dict: experts sharded over the
    expert axis, gate replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return {
        "wg": NamedSharding(mesh, P(None, None)),
        "w1": NamedSharding(mesh, P(axis, None, None)),
        "w2": NamedSharding(mesh, P(axis, None, None)),
    }
