"""Functional op zoo — the TPU-native equivalent of the reference's
gserver/layers kernels + paddle/function ops + hl_* device layer, with
autodiff replacing every hand-written backward."""

from paddle_tpu.ops import activations
from paddle_tpu.ops import attention
from paddle_tpu.ops import beam
from paddle_tpu.ops import conv
from paddle_tpu.ops import crf
from paddle_tpu.ops import ctc
from paddle_tpu.ops import embedding
from paddle_tpu.ops import initializers
from paddle_tpu.ops import linear
from paddle_tpu.ops import losses
from paddle_tpu.ops import math_ops
from paddle_tpu.ops import norm
from paddle_tpu.ops import rnn
from paddle_tpu.ops import sampling
from paddle_tpu.ops import sequence

__all__ = [
    "activations", "attention", "beam", "conv", "crf", "ctc", "embedding",
    "initializers", "linear", "losses", "math_ops", "norm", "rnn",
    "sampling", "sequence",
]
