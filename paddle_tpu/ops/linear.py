"""Dense/linear ops: fc, projections, mixed-layer combination.

Reference: FullyConnectedLayer (gserver/layers/FullyConnectedLayer.cpp),
projection zoo feeding MixedLayer (gserver/layers/Projection.h,
FullMatrixProjection, TransposedFullMatrixProjection, IdentityProjection,
DotMulProjection, ScalingProjection, DotMulOperator).  On TPU: keep matmuls
on the MXU in bfloat16, accumulate in f32 (preferred_element_type).
"""

import jax.numpy as jnp

from paddle_tpu.core import dtypes
from paddle_tpu.ops import activations


def matmul(x, w):
    """MXU-friendly matmul: bf16 inputs, >=f32 accumulation (f64 stays f64
    for the checkgrad sweeps)."""
    cd = dtypes.compute_dtype()
    acc = jnp.promote_types(cd, jnp.float32)
    return jnp.matmul(x.astype(cd), w.astype(cd),
                      preferred_element_type=acc)


def fc(x, w, b=None, act=None):
    """y = act(x @ w + b).  x: [..., in], w: [in, out], b: [out]."""
    y = matmul(x, w)
    if b is not None:
        y = y + b
    return activations.get(act)(y)


def full_matrix_projection(x, w):
    return matmul(x, w)


def trans_full_matrix_projection(x, w):
    """w stored [out, in] (reference TransposedFullMatrixProjection)."""
    return matmul(x, w.T)


def identity_projection(x, offset=0, size=None):
    if size is None:
        return x
    return x[..., offset:offset + size]


def dotmul_projection(x, w):
    """Elementwise scale by a learned vector: x * w, w: [size]."""
    return x * w


def scaling_projection(x, w):
    """Scale whole input by a learned scalar w: [1]."""
    return x * w.reshape(())


def dotmul_operator(a, b, scale=1.0):
    return scale * a * b


def linear_comb(x, w, size):
    """LinearCombinationLayer / convex_comb: weights [..., K] over K vectors
    [..., K*size] -> [..., size]."""
    k = w.shape[-1]
    xs = x.reshape(x.shape[:-1] + (k, size))
    return jnp.einsum("...k,...ks->...s", w, xs)
