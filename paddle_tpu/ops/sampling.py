"""Large-vocabulary sampled losses: NCE and hierarchical sigmoid.

Reference: NCELayer (gserver/layers/NCELayer.cpp) with MultinomialSampler
(AliasMethod-style), HierarchicalSigmoidLayer + bit-code ops
(math/MatrixBitCode.cpp).  The reference updates only sampled/visited rows
(sparse-row matrices); here the same sparsity arrives via gather + the
optimizer's sparse-row handling, and the sampled matmuls stay dense minis so
they run on the MXU.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.ops.linear import matmul


def uniform_neg_samples(rng, shape, num_classes):
    return jax.random.randint(rng, shape, 0, num_classes, dtype=jnp.int32)


def nce_loss(x, w, b, labels, neg_samples, num_classes, sample_probs=None):
    """Noise-contrastive estimation loss.

    x: [B, D] features; w: [V, D] class embeddings; b: [V];
    labels: int [B]; neg_samples: int [B, K] (pre-drawn noise ids).
    Returns [B] loss: binary CE of true class as positive + K noise ids as
    negatives, with the NCE correction log(k * P_n(w)).
    """
    k = neg_samples.shape[1]
    if sample_probs is None:
        log_pn = -jnp.log(float(num_classes))
    else:
        log_pn = jnp.log(jnp.maximum(sample_probs, 1e-20))

    def logit(ids):
        wv = w[ids]                      # [..., D]
        bv = b[ids]
        s = jnp.einsum("bd,b...d->b...", x, wv) + bv
        if sample_probs is None:
            corr = jnp.log(float(k)) + log_pn
        else:
            corr = jnp.log(float(k)) + log_pn[ids]
        return s - corr

    pos = logit(labels[:, None])[:, 0]                 # [B]
    neg = logit(neg_samples)                           # [B, K]
    loss_pos = -jax.nn.log_sigmoid(pos)
    loss_neg = -jnp.sum(jax.nn.log_sigmoid(-neg), axis=-1)
    return loss_pos + loss_neg


def _bit_codes(labels, code_len):
    """Huffman-free binary codes as in the reference (MatrixBitCode.cpp):
    class c's path visits internal nodes ((c+1) >> (d+1)) - 1 with branch bit
    ((c+1) >> d) & 1, for d = 0..code_len-1 while node index >= 0."""
    c1 = labels + 1
    ds = jnp.arange(code_len)
    nodes = (c1[..., None] >> (ds + 1)) - 1            # [..., D]
    bits = (c1[..., None] >> ds) & 1
    valid = nodes >= 0
    return jnp.maximum(nodes, 0), bits.astype(jnp.float32), valid


def hsigmoid_loss(x, w, b, labels, num_classes):
    """Hierarchical sigmoid loss (reference HierarchicalSigmoidLayer).

    x: [B, D]; w: [num_classes-1, D] internal-node weights; b: [num_classes-1];
    labels: int [B].  Returns [B] loss, computed over the ~log2(V) nodes on
    each label's path.
    """
    import math
    code_len = max(1, math.ceil(math.log2(max(num_classes, 2))))
    nodes, bits, valid = _bit_codes(labels, code_len)   # [B, L]
    wv = w[nodes]                                       # [B, L, D]
    bv = b[nodes]
    s = jnp.einsum("bd,bld->bl", x, wv) + bv
    # reference convention: cost = sum log(1 + exp(s)) - bit*s
    loss = jnp.logaddexp(0.0, s) - bits * s
    return jnp.sum(loss * valid, axis=-1)


def multinomial_alias_sample(rng, probs, shape):
    """Draw ids from an arbitrary distribution (reference MultinomialSampler;
    jax.random.categorical is the XLA-native Gumbel-max equivalent)."""
    logits = jnp.log(jnp.maximum(probs, 1e-20))
    return jax.random.categorical(rng, logits, shape=shape).astype(jnp.int32)


def top_k(x, k):
    """Top-k values/ids (reference hl_top_k.cu / Matrix::rowMax(ids, vals))."""
    vals, ids = jax.lax.top_k(x, k)
    return vals, ids.astype(jnp.int32)
