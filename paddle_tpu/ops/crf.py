"""Linear-chain CRF: log-likelihood + Viterbi decode.

Reference: CRFLayer + LinearChainCRF (gserver/layers/LinearChainCRF.{h,cpp}),
CRFDecodingLayer.  The reference parameter is a [(N+2), N] matrix: row 0 =
start transition a, row 1 = end transition b, rows 2.. = transition w[i][j]
(from tag i to tag j).  Same layout kept here so checkpoints are comparable.

Forward/backward over time = `lax.scan` with logsumexp carries; Viterbi =
scan with max+argmax carries and a reverse traceback scan.  Autodiff
provides the gradient of the partition function (the reference hand-codes
the forward-backward recursions).
"""

import jax
import jax.numpy as jnp

_NEG = -1e30


def _split_params(w):
    """w: [N+2, N] -> (start [N], end [N], trans [N, N])."""
    return w[0], w[1], w[2:]


def crf_log_likelihood(emissions, tags, lengths, w):
    """Negative log-likelihood per sequence.

    emissions: [B, T, N] unnormalized scores (the reference feeds raw layer
    output, no softmax), tags: int [B, T], lengths: [B], w: [N+2, N].
    Returns [B] loss = log Z - score(tags).
    """
    start, end, trans = _split_params(w)
    b, t, n = emissions.shape
    mask = (jnp.arange(t)[None, :] < lengths[:, None])

    # --- partition function: alpha recursion ---
    alpha0 = start[None, :] + emissions[:, 0]

    def fwd(alpha, xs):
        emit, m = xs
        # alpha': logsumexp_i(alpha_i + trans_ij) + emit_j
        scores = alpha[:, :, None] + trans[None, :, :]
        new = jax.nn.logsumexp(scores, axis=1) + emit
        return jnp.where(m[:, None], new, alpha), None

    emits_tm = emissions.transpose(1, 0, 2)[1:]
    mask_tm = mask.transpose(1, 0)[1:]
    alpha_final, _ = jax.lax.scan(fwd, alpha0, (emits_tm, mask_tm))
    log_z = jax.nn.logsumexp(alpha_final + end[None, :], axis=-1)

    # --- gold path score ---
    tags = jnp.clip(tags.astype(jnp.int32), 0, n - 1)
    emit_scores = jnp.take_along_axis(emissions, tags[..., None], axis=-1)[..., 0]
    emit_score = jnp.sum(emit_scores * mask, axis=-1)
    trans_scores = trans[tags[:, :-1], tags[:, 1:]]
    trans_score = jnp.sum(trans_scores * mask[:, 1:], axis=-1)
    first_score = start[tags[:, 0]]
    last_idx = jnp.maximum(lengths - 1, 0)
    last_tag = jnp.take_along_axis(tags, last_idx[:, None], axis=1)[:, 0]
    last_score = end[last_tag]
    gold = emit_score + trans_score + first_score + last_score
    return log_z - gold


def crf_decode(emissions, lengths, w):
    """Viterbi decode -> (tags [B, T] int32, best_score [B])."""
    start, end, trans = _split_params(w)
    b, t, n = emissions.shape
    mask = (jnp.arange(t)[None, :] < lengths[:, None])

    delta0 = start[None, :] + emissions[:, 0]

    def fwd(delta, xs):
        emit, m = xs
        scores = delta[:, :, None] + trans[None, :, :]      # [B, N, N]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)
        new = jnp.max(scores, axis=1) + emit
        delta_out = jnp.where(m[:, None], new, delta)
        # where masked, traceback points to self so the path freezes
        bp = jnp.where(m[:, None], best_prev,
                       jnp.arange(n, dtype=jnp.int32)[None, :])
        return delta_out, bp

    emits_tm = emissions.transpose(1, 0, 2)[1:]
    mask_tm = mask.transpose(1, 0)[1:]
    delta_final, bps = jax.lax.scan(fwd, delta0, (emits_tm, mask_tm))
    final_scores = delta_final + end[None, :]
    best_last = jnp.argmax(final_scores, axis=-1).astype(jnp.int32)
    best_score = jnp.max(final_scores, axis=-1)

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, prev

    _, tags_rev = jax.lax.scan(back, best_last, bps, reverse=True)
    tags = jnp.concatenate([tags_rev, best_last[None]], axis=0).transpose(1, 0)
    return tags * mask.astype(jnp.int32), best_score
