"""Embedding / table projection.

Reference: TableProjection + SparseRowMatrix family
(math/SparseRowMatrix.h:29-299, gserver/layers/TableProjection.cpp).  The
reference's sparse-row prefetch/update machinery becomes a plain gather here;
sparse *updates* are recovered by the optimizer's sparse-row path
(paddle_tpu.optim) and by sharding the table over the mesh's model axis for
large vocabularies (paddle_tpu.parallel.sharding).
"""

import jax.numpy as jnp


def embedding_lookup(table, ids):
    """table: [vocab, dim], ids: int [...] -> [..., dim].

    Out-of-range ids (e.g. padding -1) return zeros.
    """
    valid = (ids >= 0) & (ids < table.shape[0])
    safe = jnp.clip(ids, 0, table.shape[0] - 1)
    out = jnp.take(table, safe, axis=0)
    return out * valid[..., None].astype(out.dtype)


def one_hot(ids, depth, dtype=jnp.float32):
    """Out-of-range ids (padding) give all-zero rows, matching
    embedding_lookup's convention."""
    import jax
    return jax.nn.one_hot(ids, depth, dtype=dtype)
