"""CTC loss (dynamic-programming forward), TPU-native.

Reference: CTCLayer + LinearChainCTC (gserver/layers/LinearChainCTC.cpp) and
WarpCTCLayer (dlopen'd warp-ctc).  Here one implementation: the standard
alpha recursion over the extended label sequence (blanks interleaved), run as
`lax.scan` over time in log space, vectorized over batch and label positions
— no per-sample loops, static shapes, autodiff supplies the gradient.

Convention: blank = 0 by default (the reference uses num_classes as blank in
warpctc and 0 in LinearChainCTC; configurable here).
"""

import jax
import jax.numpy as jnp

_NEG = -1e30


def ctc_loss(log_probs, logit_lengths, labels, label_lengths, blank=0):
    """Per-sample CTC negative log-likelihood.

    log_probs: [B, T, C] log-softmax outputs; logit_lengths: [B];
    labels: int [B, L] (padded with anything); label_lengths: [B].
    Returns [B] loss.
    """
    b, t, c = log_probs.shape
    l = labels.shape[1]
    s = 2 * l + 1  # extended sequence: blank label blank label ... blank

    labels = jnp.clip(labels.astype(jnp.int32), 0, c - 1)
    # extended label sequence ids [B, S]
    ext = jnp.full((b, s), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels)

    # allowed skip: alpha[s] can come from s-2 if ext[s] != blank and
    # ext[s] != ext[s-2]
    ext_prev2 = jnp.pad(ext[:, :-2], ((0, 0), (2, 0)), constant_values=-1)
    can_skip = (ext != blank) & (ext != ext_prev2)

    # positions beyond 2*label_len are invalid
    pos = jnp.arange(s)[None, :]
    valid_pos = pos < (2 * label_lengths[:, None] + 1)

    def emit(t_idx):
        # log_probs at time t for each extended position: [B, S]
        lp = log_probs[:, t_idx]                   # [B, C]
        return jnp.take_along_axis(lp, ext, axis=1)

    alpha0 = jnp.full((b, s), _NEG)
    e0 = emit(0)
    alpha0 = alpha0.at[:, 0].set(e0[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(label_lengths > 0, e0[:, 1], _NEG))
    alpha0 = jnp.where(valid_pos, alpha0, _NEG)

    def step(alpha, t_idx):
        stay = alpha
        prev1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)), constant_values=_NEG)
        prev2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)), constant_values=_NEG)
        prev2 = jnp.where(can_skip, prev2, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2) + emit(t_idx)
        merged = jnp.where(valid_pos, merged, _NEG)
        # freeze past the logit length
        active = (t_idx < logit_lengths)[:, None]
        return jnp.where(active, merged, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t))

    # final: last blank or last label position
    last = 2 * label_lengths  # index of final blank
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    a_prev = jnp.where(label_lengths > 0, a_prev, _NEG)
    ll = jnp.logaddexp(a_last, a_prev)
    return -ll


def ctc_greedy_decode(log_probs, logit_lengths, blank=0):
    """Best-path decode: argmax per step, collapse repeats, drop blanks.
    Returns (ids [B, T] int32 padded with -1, lengths [B])."""
    ids = jnp.argmax(log_probs, axis=-1).astype(jnp.int32)   # [B, T]
    t = ids.shape[1]
    step_mask = jnp.arange(t)[None, :] < logit_lengths[:, None]
    prev = jnp.pad(ids[:, :-1], ((0, 0), (1, 0)), constant_values=-1)
    keep = (ids != blank) & (ids != prev) & step_mask

    # stable compaction: position of each kept element
    kept_pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out = jnp.full_like(ids, -1)
    scatter_idx = jnp.where(keep, kept_pos, t - 1)
    # scatter kept ids; colliding writes at t-1 are later overwritten by -1 pad fix
    out = jax.vmap(lambda o, idx, v, k: o.at[idx].set(jnp.where(k, v, o[idx])))(
        out, scatter_idx, ids, keep)
    lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
    # clean anything at/after length
    pos = jnp.arange(t)[None, :]
    out = jnp.where(pos < lengths[:, None], out, -1)
    return out, lengths
