"""Attention ops.

Reference: `simple_attention` composite (trainer_config_helpers/networks.py:1273)
— additive (Bahdanau) attention built from fc + sequence ops for the seqToseq
NMT demo.  Plus TPU-era capabilities the reference lacks: scaled dot-product
multi-head attention (for the Transformer model family) with masking, built
to fuse on the MXU; the sequence-parallel ring variant lives in
paddle_tpu.parallel.ring_attention.
"""

import functools
import os

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops.linear import matmul

_NEG = -1e30

# dense fallback materializes [B, H, Tq, Tk] f32 logits; beyond this many
# logit elements per head-batch, route to the O(T)-memory chunked path
_CHUNKED_MIN = int(os.environ.get("PADDLE_TPU_CHUNKED_ATTN_MIN",
                                  str(2048 * 2048)))


def additive_attention_scores(enc_proj: SequenceBatch, dec_state_proj, v):
    """Bahdanau scores: v . tanh(enc_proj + dec_proj).

    enc_proj.data: [B, T, A] (precomputed once per sequence — hoisted out of
    the decode loop, as the reference does with encoded_proj), dec_state_proj:
    [B, A], v: [A] -> [B, T] masked scores.
    """
    e = jnp.tanh(enc_proj.data + dec_state_proj[:, None, :])
    scores = jnp.einsum("bta,a->bt", e, v)
    return jnp.where(enc_proj.bool_mask(), scores, _NEG)


def attention_context(scores, values: SequenceBatch):
    """softmax(scores) @ values -> [B, D]."""
    w = jax.nn.softmax(scores, axis=-1)
    w = w * values.mask(w.dtype)
    w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-9)
    return jnp.einsum("bt,btd->bd", w, values.data)


def online_softmax_block(q, k, v, m_prev, l_prev, acc, mask=None,
                         scale=1.0, acc_dtype=jnp.float32):
    """One K/V block of flash-style attention — THE shared numerically
    delicate accumulation (used by chunked_attention here and the ring
    rotation in parallel/ring_attention.py).

    q: [..., Tq, D], k/v: [..., Tk, D]; m/l: [..., Tq]; acc: [..., Tq, D];
    mask: optional bool [..., Tq, Tk].  Returns updated (m, l, acc)."""
    s = jnp.einsum("...qd,...kd->...qk", q, k,
                   preferred_element_type=acc_dtype) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    # fully-masked blocks (max == _NEG): exp underflows to 0, harmless
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p.astype(v.dtype), v)
    return m_new, l_new, acc_new


def chunked_attention(q, k, v, scale=None, causal=False, key_mask=None,
                      q_chunk=512, k_chunk=512, q_segment_ids=None,
                      kv_segment_ids=None):
    """Flash-style attention in pure XLA: online-softmax accumulation over
    key chunks inside a scan over query chunks — O(T) memory on ANY
    backend (the CPU/interpret twin of ops.pallas.flash_attention, and the
    dense fallback's long-context escape hatch).  The key-chunk body is
    rematerialized, so the backward pass recomputes blocks instead of
    saving [Tq, Tk] intermediates.

    q: [B, H, Tq, D], k/v: [B, H, Tk, D]; key_mask: optional [B, Tk]
    validity (per-key, O(T) — a full [Tq, Tk] mask would defeat the
    point).  causal matches the dense path's tril offset (query i attends
    keys <= i + Tk - Tq).

    q_segment_ids/kv_segment_ids: [B, T] int segment labels for PACKED
    batches (core.sequence.pack_sequences) — attention is block-diagonal
    per segment (q attends k iff labels match), computed per chunk pair so
    the [Tq, Tk] segment mask is never materialized.  Padding rows carry
    a label real segments never use."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = scale if scale is not None else 1.0 / (float(d) ** 0.5)
    q_chunk, k_chunk = min(q_chunk, tq), min(k_chunk, tk)
    pq, pk_ = (-tq) % q_chunk, (-tk) % k_chunk
    if pq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pq), (0, 0)))
    if pk_ or key_mask is None:
        # padded keys must be masked out; build the O(T) validity vector
        km = jnp.ones((b, tk), q.dtype) if key_mask is None \
            else key_mask.astype(q.dtype)
        key_mask = jnp.pad(km, ((0, 0), (0, pk_)))
    if pk_:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pk_), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pk_), (0, 0)))
    if kv_segment_ids is not None and q_segment_ids is None:
        raise ValueError(
            "kv_segment_ids without q_segment_ids: label the query side "
            "too (a lone KV labeling would be silently dropped)")
    segmented = q_segment_ids is not None
    if segmented:
        # pad labels with two DIFFERENT sentinels so padded q never
        # matches padded k (and neither matches a real segment)
        q_seg = jnp.pad(q_segment_ids.astype(jnp.int32),
                        ((0, 0), (0, pq)), constant_values=-1)
        kv_seg = jnp.pad((q_segment_ids if kv_segment_ids is None
                          else kv_segment_ids).astype(jnp.int32),
                         ((0, 0), (0, pk_)), constant_values=-2)
    nq, nk = (tq + pq) // q_chunk, (tk + pk_) // k_chunk
    qs = q.reshape(b, h, nq, q_chunk, d).transpose(2, 0, 1, 3, 4)
    ks = k.reshape(b, h, nk, k_chunk, d).transpose(2, 0, 1, 3, 4)
    vs = v.reshape(b, h, nk, k_chunk, d).transpose(2, 0, 1, 3, 4)
    kms = key_mask.reshape(b, nk, k_chunk).transpose(1, 0, 2)
    qsegs = (q_seg.reshape(b, nq, q_chunk).transpose(1, 0, 2)
             if segmented else jnp.zeros((nq, b, q_chunk), jnp.int32))
    ksegs = (kv_seg.reshape(b, nk, k_chunk).transpose(1, 0, 2)
             if segmented else jnp.zeros((nk, b, k_chunk), jnp.int32))
    off = tk - tq   # dense path's tril offset
    # f64 inputs keep f64 accumulation, matching the dense path's
    # promote_types behavior (no silent precision drop above the threshold)
    acc_dtype = jnp.promote_types(q.dtype, jnp.float32)

    @jax.checkpoint
    def k_body(carry, inp, q_blk, qi, qseg_blk):
        m, l, acc = carry
        k_blk, v_blk, km_blk, kseg_blk, ki = inp
        keep = km_blk[:, None, None, :] > 0
        if segmented:
            keep = keep & (qseg_blk[:, :, None]
                           == kseg_blk[:, None, :])[:, None]
        if causal:
            qpos = qi * q_chunk + jnp.arange(q_chunk) + off
            kpos = ki * k_chunk + jnp.arange(k_chunk)
            keep = keep & (qpos[:, None] >= kpos[None, :])[None, None]

        def update(carry):
            return online_softmax_block(q_blk, k_blk, v_blk, *carry,
                                        mask=keep, scale=scale,
                                        acc_dtype=acc_dtype)
        if not causal:
            return update(carry), None
        # skip key blocks entirely above the diagonal (~half the FLOPs at
        # long context, same trick as the flash kernel's block indexing)
        needed = qi * q_chunk + (q_chunk - 1) + off >= ki * k_chunk
        return jax.lax.cond(needed, update, lambda c: c, carry), None

    def q_body(_, inp):
        q_blk, qi, qseg_blk = inp
        init = (jnp.full((b, h, q_chunk), _NEG, acc_dtype),
                jnp.zeros((b, h, q_chunk), acc_dtype),
                jnp.zeros((b, h, q_chunk, d), acc_dtype))
        (m, l, acc), _ = jax.lax.scan(
            functools.partial(k_body, q_blk=q_blk, qi=qi,
                              qseg_blk=qseg_blk), init,
            (ks, vs, kms, ksegs, jnp.arange(nk)))
        return None, (acc / jnp.maximum(l[..., None], 1e-20)).astype(q.dtype)

    _, outs = jax.lax.scan(q_body, None, (qs, jnp.arange(nq), qsegs))
    out = outs.transpose(1, 2, 0, 3, 4).reshape(b, h, nq * q_chunk, d)
    return out[:, :, :tq]


def dot_product_attention(q, k, v, mask=None, scale=None, causal=False,
                          use_flash=None, key_mask=None,
                          q_segment_ids=None, kv_segment_ids=None):
    """q: [B, H, Tq, Dh], k/v: [B, H, Tk, Dh] -> [B, H, Tq, Dh].

    Softmax in f32 (TPU numerics), logits computed on the MXU in bf16.
    On TPU, unmasked block-aligned shapes route to the Pallas flash
    kernel (ops.pallas.flash_attention) — O(T) HBM instead of O(T^2);
    elsewhere, shapes whose logits would exceed PADDLE_TPU_CHUNKED_ATTN_MIN
    elements route to chunked_attention (same O(T) memory in pure XLA).

    key_mask: [B, Tk] per-key validity — the O(T) way to express padding
    (a full [Tq, Tk] `mask` forces the dense path and O(T^2) memory).
    Padded QUERY rows are not specially masked: they produce garbage that
    positionwise downstream ops keep local and masked losses drop.
    """
    if mask is not None and key_mask is not None:
        raise ValueError("pass mask or key_mask, not both")
    segmented = q_segment_ids is not None
    if kv_segment_ids is not None and not segmented:
        raise ValueError(
            "kv_segment_ids without q_segment_ids: label the query side "
            "too (a lone KV labeling would be silently dropped)")
    if use_flash and (mask is not None or key_mask is not None
                      or segmented):
        raise ValueError("the flash kernel has no mask support; drop "
                         "use_flash=True or the masking")
    if use_flash is None:
        from paddle_tpu.ops import pallas as pk
        use_flash = (pk.use_pallas() and mask is None and key_mask is None
                     and not segmented
                     and q.shape[2] % 128 == 0 and k.shape[2] % 128 == 0
                     and (not causal or q.shape[2] == k.shape[2]))
    if use_flash:
        from paddle_tpu.ops.pallas import flash_attention
        return flash_attention(q, k, v, scale=scale, causal=causal)
    if mask is None and q.shape[2] * k.shape[2] >= _CHUNKED_MIN:
        return chunked_attention(q, k, v, scale=scale, causal=causal,
                                 key_mask=key_mask,
                                 q_segment_ids=q_segment_ids,
                                 kv_segment_ids=kv_segment_ids)
    if segmented:
        seg = segment_mask(q_segment_ids, kv_segment_ids)
        mask = seg if mask is None else (mask & seg)
        if key_mask is not None:
            mask = mask & (key_mask[:, None, None, :] > 0)
    elif key_mask is not None:
        mask = key_mask[:, None, None, :] > 0
    dh = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(float(dh))
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k,
        preferred_element_type=jnp.promote_types(q.dtype, jnp.float32)) * scale
    if causal:
        tq, tk = logits.shape[-2:]
        cm = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        logits = jnp.where(cm, logits, _NEG)
    if mask is not None:
        logits = jnp.where(mask, logits, _NEG)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v)


def repeat_kv_heads(kv, num_heads):
    """[B, Hkv, T, D] -> [B, H, T, D] by repeating each KV head over its
    query group (GQA).  H % Hkv must hold; Hkv == H is a no-op."""
    hkv = kv.shape[1]
    if hkv == num_heads:
        return kv
    if num_heads % hkv:
        raise ValueError(f"num_heads={num_heads} not divisible by "
                         f"num_kv_heads={hkv}")
    return jnp.repeat(kv, num_heads // hkv, axis=1)


def multi_head_attention(x_q, x_kv, wq, wk, wv, wo, num_heads, mask=None,
                         causal=False, key_mask=None, mesh=None,
                         seq_axis="seq", zigzag=False,
                         q_segment_ids=None, kv_segment_ids=None,
                         rope_positions=None):
    """Dense multi-head attention.  x_q: [B, Tq, D], x_kv: [B, Tk, D],
    wq/wk/wv: [D, D], wo: [D, D].  key_mask: [B, Tk] padding validity
    (O(T); preferred over a materialized [Tq, Tk] mask).

    mesh: when given with a >1 `seq_axis`, attention runs SEQUENCE-
    PARALLEL through the ppermute ring (parallel/ring_attention): callers
    shard T over that axis and each device holds T/n — the long-context
    training plane.  Requires key_mask-style masking (a 2-D mask has no
    O(T) sharding).  q_segment_ids/kv_segment_ids compose with the ring:
    the KV labels rotate with K/V so packed rows stay block-diagonal
    per segment under sequence parallelism (zigzag included — permute
    the labels like the tokens)."""
    b, tq, d = x_q.shape
    tk = x_kv.shape[1]
    dh = d // num_heads
    # GQA is carried by the WEIGHT SHAPES: wk/wv projecting to fewer
    # than num_heads*dh columns mean grouped KV heads (transformer.init
    # num_kv_heads=)
    if wk.shape[1] % dh:
        raise ValueError(f"wk projects to {wk.shape[1]} dims, not a "
                         f"multiple of head dim {dh}")
    if wv.shape[1] != wk.shape[1]:
        raise ValueError(f"wk ({wk.shape[1]}) and wv ({wv.shape[1]}) "
                         "must project to the same grouped-KV width")
    hkv = wk.shape[1] // dh

    def split(x, w, t, h):
        return matmul(x, w).reshape(b, t, h, dh).transpose(0, 2, 1, 3)

    q = split(x_q, wq, tq, num_heads)
    # GQA: k/v project to fewer heads (wk/wv are [D, dh*Hkv]) and each
    # serves a GROUP of query heads — the serving lever is the smaller
    # KV cache (models/transformer init_lm_cache sizes off these
    # shapes).  The ring paths carry the GROUPED stripes through the
    # ppermute hops and expand per hop in registers (ring traffic
    # shrinks by num_heads/Hkv); the local paths repeat up to full
    # heads below, after the ring decision.
    k = split(x_kv, wk, tk, hkv)
    v = split(x_kv, wv, tk, hkv)
    if rope_positions is not None:
        # rotary positions on q/k before any masking or sharding
        # (self-attention: one positions array serves both sides)
        if tq != tk:
            raise ValueError(
                "rope_positions requires self-attention (Tq == Tk); "
                "cross-attention has no shared position stream")
        q = rope(q, rope_positions)
        k = rope(k, rope_positions)
    ring_active = mesh is not None and mesh.shape.get(seq_axis, 1) > 1
    if zigzag and not (ring_active and causal):
        # fail fast: zigzag-ordered inputs under a plain causal mask would
        # silently attend the future (mirrors transformer.decode's guard)
        raise ValueError("zigzag=True requires causal=True and a mesh "
                         f"whose {seq_axis!r} axis is > 1")
    if ring_active:
        if mask is not None:
            raise ValueError("sequence-parallel attention needs key_mask "
                             "masking, not a materialized 2-D mask")
        if causal and tq != tk:
            raise ValueError(
                "sequence-parallel causal attention requires Tq == Tk "
                "(the ring has no tril-offset convention for unequal "
                "lengths; self-attention always satisfies this)")
        if zigzag and causal:
            # balanced causal ring: caller feeds zigzag-ordered sequences
            # (see parallel.ring_attention.zigzag_permute) — halved AND
            # load-balanced attention per ring step.  Segment labels (if
            # any) must be zigzag-permuted alongside the tokens.
            from paddle_tpu.parallel.ring_attention import (
                ring_attention_zigzag)
            out = ring_attention_zigzag(q, k, v, mesh, axis_name=seq_axis,
                                        kv_mask=key_mask,
                                        q_segment_ids=q_segment_ids,
                                        kv_segment_ids=kv_segment_ids)
        else:
            from paddle_tpu.parallel.ring_attention import ring_attention
            out = ring_attention(q, k, v, mesh, axis_name=seq_axis,
                                 causal=causal, kv_mask=key_mask,
                                 q_segment_ids=q_segment_ids,
                                 kv_segment_ids=kv_segment_ids)
    else:
        out = dot_product_attention(q, repeat_kv_heads(k, num_heads),
                                    repeat_kv_heads(v, num_heads),
                                    mask=mask, causal=causal,
                                    key_mask=key_mask,
                                    q_segment_ids=q_segment_ids,
                                    kv_segment_ids=kv_segment_ids)
    out = out.transpose(0, 2, 1, 3).reshape(b, tq, d)
    return matmul(out, wo)


def rope(x, positions, base=10000.0):
    """Rotary position embedding: rotate head-dim pairs of x [..., H, T, D]
    by per-position angles (RoFormer).  positions: [T] or [B, T] int —
    PACKED rows pass within-segment positions, so every segment sees
    positions starting at 0 exactly as if it ran alone; attention scores
    depend only on RELATIVE position, which is what lets a rope model
    run sequences longer than anything seen in training (no learned
    table to outgrow).  Applied to q and k BEFORE attention, it composes
    unchanged with the ring/zigzag sharding (rotation is positionwise;
    the rotated K blocks travel the ring like any other)."""
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f"rope needs an even head dim, got {d}")
    half = d // 2
    freq = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    pos = jnp.asarray(positions, jnp.float32)
    ang = pos[..., None] * freq                    # [..., T, half]
    if ang.ndim == 2:                              # positions [T]
        ang = ang[None, None]                      # -> [1, 1, T, half]
    else:                                          # positions [B, T]
        ang = ang[:, None]                         # -> [B, 1, T, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos],
        axis=-1).astype(x.dtype)


def segment_mask(q_segment_ids, kv_segment_ids=None):
    """[B, Tq], [B, Tk] int labels -> [B, 1, Tq, Tk] block-diagonal
    attention mask for PACKED batches (label 0 = padding, never matches).
    O(T^2) — for long context pass the labels to chunked_attention
    instead, which applies them per chunk pair."""
    kv = q_segment_ids if kv_segment_ids is None else kv_segment_ids
    same = q_segment_ids[:, None, :, None] == kv[:, None, None, :]
    return same & (q_segment_ids[:, None, :, None] > 0) \
        & (kv[:, None, None, :] > 0)


def padding_mask(q_len_mask, k_len_mask):
    """[B, Tq], [B, Tk] -> [B, 1, Tq, Tk] boolean attention mask.

    O(T^2) memory and forces the dense attention path — prefer passing
    the [B, Tk] validity vector as dot_product_attention's `key_mask`
    (O(T), routes to flash-style chunking at long context).  Kept for
    callers that genuinely need a 2-D mask (e.g. blockwise or relative
    masking)."""
    return (q_len_mask[:, None, :, None] > 0) & (k_len_mask[:, None, None, :] > 0)
