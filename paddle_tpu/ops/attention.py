"""Attention ops.

Reference: `simple_attention` composite (trainer_config_helpers/networks.py:1273)
— additive (Bahdanau) attention built from fc + sequence ops for the seqToseq
NMT demo.  Plus TPU-era capabilities the reference lacks: scaled dot-product
multi-head attention (for the Transformer model family) with masking, built
to fuse on the MXU; the sequence-parallel ring variant lives in
paddle_tpu.parallel.ring_attention.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops.linear import matmul

_NEG = -1e30


def additive_attention_scores(enc_proj: SequenceBatch, dec_state_proj, v):
    """Bahdanau scores: v . tanh(enc_proj + dec_proj).

    enc_proj.data: [B, T, A] (precomputed once per sequence — hoisted out of
    the decode loop, as the reference does with encoded_proj), dec_state_proj:
    [B, A], v: [A] -> [B, T] masked scores.
    """
    e = jnp.tanh(enc_proj.data + dec_state_proj[:, None, :])
    scores = jnp.einsum("bta,a->bt", e, v)
    return jnp.where(enc_proj.bool_mask(), scores, _NEG)


def attention_context(scores, values: SequenceBatch):
    """softmax(scores) @ values -> [B, D]."""
    w = jax.nn.softmax(scores, axis=-1)
    w = w * values.mask(w.dtype)
    w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-9)
    return jnp.einsum("bt,btd->bd", w, values.data)


def dot_product_attention(q, k, v, mask=None, scale=None, causal=False,
                          use_flash=None):
    """q: [B, H, Tq, Dh], k/v: [B, H, Tk, Dh] -> [B, H, Tq, Dh].

    Softmax in f32 (TPU numerics), logits computed on the MXU in bf16.
    On TPU, unmasked block-aligned shapes route to the Pallas flash
    kernel (ops.pallas.flash_attention) — O(T) HBM instead of O(T^2).
    """
    if use_flash is None:
        from paddle_tpu.ops import pallas as pk
        use_flash = (pk.use_pallas() and mask is None
                     and q.shape[2] % 128 == 0 and k.shape[2] % 128 == 0
                     and (not causal or q.shape[2] == k.shape[2]))
    if use_flash:
        from paddle_tpu.ops.pallas import flash_attention
        return flash_attention(q, k, v, scale=scale, causal=causal)
    dh = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(float(dh))
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k,
        preferred_element_type=jnp.promote_types(q.dtype, jnp.float32)) * scale
    if causal:
        tq, tk = logits.shape[-2:]
        cm = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        logits = jnp.where(cm, logits, _NEG)
    if mask is not None:
        logits = jnp.where(mask, logits, _NEG)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v)


def multi_head_attention(x_q, x_kv, wq, wk, wv, wo, num_heads, mask=None,
                         causal=False):
    """Dense multi-head attention.  x_q: [B, Tq, D], x_kv: [B, Tk, D],
    wq/wk/wv: [D, D], wo: [D, D]."""
    b, tq, d = x_q.shape
    tk = x_kv.shape[1]
    dh = d // num_heads

    def split(x, w, t):
        return matmul(x, w).reshape(b, t, num_heads, dh).transpose(0, 2, 1, 3)

    q = split(x_q, wq, tq)
    k = split(x_kv, wk, tk)
    v = split(x_kv, wv, tk)
    out = dot_product_attention(q, k, v, mask=mask, causal=causal)
    out = out.transpose(0, 2, 1, 3).reshape(b, tq, d)
    return matmul(out, wo)


def padding_mask(q_len_mask, k_len_mask):
    """[B, Tq], [B, Tk] -> [B, 1, Tq, Tk] boolean attention mask."""
    return (q_len_mask[:, None, :, None] > 0) & (k_len_mask[:, None, None, :] > 0)
