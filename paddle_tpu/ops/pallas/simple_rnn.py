"""Fused whole-sequence vanilla RNN (Pallas) — completes the recurrent
kernel family (reference RecurrentLayer, gserver/layers/RecurrentLayer.cpp:
h_t = act(x_t + h_{t-1} @ W)).

Same design as ops/pallas/{lstm,gru}.py: the grid is the time loop, W stays
VMEM-resident, h in VMEM scratch.  tanh only (the reference default);
other activations use the scan.  Backward is the time-reversed BPTT kernel
with an in-VMEM dW accumulator; reverse direction via the caller's
time-flip (see gru.py note).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.common import LANES as _LANES, lanes as _lanes


def _fwd_kernel(xs_ref, w_ref, mask_ref, hs_ref, h_scr, *, d):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = jnp.zeros_like(h_scr)

    h = h_scr[:]
    x = xs_ref[0].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    h_new = jnp.tanh(x + jax.lax.dot_general(
        h, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32))
    m = _lanes(mask_ref[0], d)
    h = m * h_new + (1.0 - m) * h
    h_scr[:] = h
    hs_ref[0] = h.astype(hs_ref.dtype)


def _bwd_kernel(hs_ref, hsp_ref, w_ref, mask_ref, dh_out_ref,
                dxs_ref, dw_ref, dh_scr, dw_scr, *, d, nt):
    j = pl.program_id(0)
    t = nt - 1 - j

    @pl.when(j == 0)
    def _():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dw_scr[:] = jnp.zeros_like(dw_scr)

    h_t = hs_ref[0].astype(jnp.float32)
    h_prev = jnp.where(t == 0, 0.0, hsp_ref[0].astype(jnp.float32))
    w = w_ref[:].astype(jnp.float32)
    m = _lanes(mask_ref[0], d)

    dh = dh_scr[:] + dh_out_ref[0].astype(jnp.float32)
    # h_t on active steps is tanh(pre); (1 - h^2) is its derivative.  On
    # masked steps h_t is the frozen carry, but dg is masked out anyway.
    dg = dh * (1.0 - h_t * h_t) * m
    dh_prev = jax.lax.dot_general(dg, w, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
    dh_scr[:] = m * dh_prev + (1.0 - m) * dh
    dw_scr[:] = dw_scr[:] + jax.lax.dot_general(
        h_prev, dg, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dxs_ref[0] = dg.astype(dxs_ref.dtype)

    @pl.when(j == nt - 1)
    def _():
        dw_ref[:] = dw_scr[:]


def _fwd(xs, w, mask, interpret):
    nt, b, d = xs.shape
    return pl.pallas_call(
        functools.partial(_fwd_kernel, d=d),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, b, d), lambda t: (t, 0, 0)),
            pl.BlockSpec((d, d), lambda t: (0, 0)),
            pl.BlockSpec((1, b, _LANES), lambda t: (t, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, b, d), lambda t: (t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nt, b, d), xs.dtype),
        scratch_shapes=[pltpu.VMEM((b, d), jnp.float32)],
        interpret=interpret,
    )(xs, w, mask)


def _bwd(interpret, res, dh_out):
    w, mask, hs = res
    nt, b, d = dh_out.shape
    dxs, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, d=d, nt=nt),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, b, d), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((1, b, d),
                         lambda j: (jnp.maximum(nt - 2 - j, 0), 0, 0)),
            pl.BlockSpec((d, d), lambda j: (0, 0)),
            pl.BlockSpec((1, b, _LANES), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((1, b, d), lambda j: (nt - 1 - j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, b, d), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((d, d), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nt, b, d), hs.dtype),
            jax.ShapeDtypeStruct((d, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, d), jnp.float32),
            pltpu.VMEM((d, d), jnp.float32),
        ],
        interpret=interpret,
    )(hs, hs, w, mask, dh_out)
    return dxs, dw.astype(w.dtype), None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused(xs, w, mask, interpret):
    return _fwd(xs, w, mask, interpret)


def _fused_fwd_rule(xs, w, mask, interpret):
    hs = _fwd(xs, w, mask, interpret)
    return hs, (w, mask, hs)


_fused.defvjp(_fused_fwd_rule, _bwd)


def vmem_bytes(b, d):
    """Backward-pass VMEM planning estimate: W + dW accumulator (2dd f32)
    + dh scratch + streamed per-step blocks."""
    resident = 2 * d * d + b * d
    streamed = 4 * b * d + _LANES * b
    return 4 * (resident + streamed)


def supported(b, d, act, init_state):
    # VMEM guard rationale: see lstm.supported
    from paddle_tpu.ops.pallas.common import vmem_budget_bytes
    return (act == "tanh" and init_state is None
            and b % 8 == 0 and d % _LANES == 0
            and vmem_bytes(b, d) <= vmem_budget_bytes())


def simple_rnn_fused(xs_tm, mask_tm, w, interpret=None):
    """xs_tm: [T, B, D] pre-projected inputs (bias included); mask [T, B].
    Returns (hs_tm, final h)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nt, b, d = xs_tm.shape
    mask_r = jnp.broadcast_to(
        mask_tm.astype(jnp.float32)[:, :, None], (nt, b, _LANES))
    hs = _fused(xs_tm, w, mask_r, interpret)
    return hs, hs[-1]
