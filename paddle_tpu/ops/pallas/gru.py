"""Fused whole-sequence GRU (Pallas) — companion to ops/pallas/lstm.py,
covering the reference's fused GRU kernels (cuda/src/hl_cuda_gru.cu +
hl_gru_ops.cuh:37-80, dispatched from GruCompute; gate layout
[update, reset, candidate], h = prev + u*(c~ - prev)).

Same design as the LSTM kernel: the grid is the time loop, w_gate/w_state
stay VMEM-resident, h lives in VMEM scratch; each step streams one [B, 3D]
gate input in and one [B, D] output out.  The inference variant emits only
hs; the VJP variant additionally saves the activated (u, r, c~) for the
time-reversed BPTT kernel, which accumulates dW_gate/dW_state in VMEM.

Numerics proven equal to the lax.scan path by tests/test_pallas_gru.py.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.common import LANES as _LANES, lanes as _lanes


def _step(x3, h, wg, ws, d):
    ru = jax.lax.dot_general(h, wg, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    u = jax.nn.sigmoid(x3[:, 0:d] + ru[:, 0:d])
    r = jax.nn.sigmoid(x3[:, d:2 * d] + ru[:, d:2 * d])
    s = r * h
    cc = jnp.tanh(x3[:, 2 * d:3 * d] + jax.lax.dot_general(
        s, ws, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32))
    return u, r, cc, h + u * (cc - h)


def _fwd_kernel(xs_ref, wg_ref, ws_ref, mask_ref, hs_ref, acts_ref, h_scr,
                *, d, save_residuals):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = jnp.zeros_like(h_scr)

    h = h_scr[:]
    x3 = xs_ref[0].astype(jnp.float32)
    wg = wg_ref[:].astype(jnp.float32)
    ws = ws_ref[:].astype(jnp.float32)
    u, r, cc, h_new = _step(x3, h, wg, ws, d)
    m = _lanes(mask_ref[0], d)
    h = m * h_new + (1.0 - m) * h
    h_scr[:] = h
    hs_ref[0] = h.astype(hs_ref.dtype)
    if save_residuals:
        acts_ref[0, :, 0:d] = u
        acts_ref[0, :, d:2 * d] = r
        acts_ref[0, :, 2 * d:3 * d] = cc


def _bwd_kernel(acts_ref, hsp_ref, wg_ref, ws_ref, mask_ref, dh_out_ref,
                dxs_ref, dwg_ref, dws_ref,
                dh_scr, dwg_scr, dws_scr, *, d, nt):
    j = pl.program_id(0)
    t = nt - 1 - j

    @pl.when(j == 0)
    def _():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dwg_scr[:] = jnp.zeros_like(dwg_scr)
        dws_scr[:] = jnp.zeros_like(dws_scr)

    u = acts_ref[0, :, 0:d]
    r = acts_ref[0, :, d:2 * d]
    cc = acts_ref[0, :, 2 * d:3 * d]
    h_prev = jnp.where(t == 0, 0.0, hsp_ref[0].astype(jnp.float32))
    wg = wg_ref[:].astype(jnp.float32)
    ws = ws_ref[:].astype(jnp.float32)
    m = _lanes(mask_ref[0], d)

    dh = dh_scr[:] + dh_out_ref[0].astype(jnp.float32)
    du = dh * (cc - h_prev)
    dug = du * u * (1.0 - u)
    dcc = dh * u
    dccg = dcc * (1.0 - cc * cc)
    ds = jax.lax.dot_general(dccg, ws, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dr = ds * h_prev
    drg = dr * r * (1.0 - r)
    dgates = jnp.concatenate([dug, drg], axis=1) * _lanes(mask_ref[0], 2 * d)
    dccg_m = dccg * m
    # active-step h_prev grad: direct (1-u) + via s=r*h_prev + via w_gate
    dh_prev = (dh * (1.0 - u) + ds * r
               + jax.lax.dot_general(dgates, wg, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32))
    dh_scr[:] = m * dh_prev + (1.0 - m) * dh
    dwg_scr[:] = dwg_scr[:] + jax.lax.dot_general(
        h_prev, dgates, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    s = r * h_prev
    dws_scr[:] = dws_scr[:] + jax.lax.dot_general(
        s, dccg_m, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dxs_ref[0, :, 0:d] = dgates[:, 0:d].astype(dxs_ref.dtype)
    dxs_ref[0, :, d:2 * d] = dgates[:, d:2 * d].astype(dxs_ref.dtype)
    dxs_ref[0, :, 2 * d:3 * d] = dccg_m.astype(dxs_ref.dtype)

    @pl.when(j == nt - 1)
    def _():
        dwg_ref[:] = dwg_scr[:]
        dws_ref[:] = dws_scr[:]


def _fwd(xs, w_gate, w_state, mask, interpret, save_residuals):
    nt, b, g = xs.shape
    d = g // 3
    out_specs = [pl.BlockSpec((1, b, d), lambda t: (t, 0, 0))]
    out_shape = [jax.ShapeDtypeStruct((nt, b, d), xs.dtype)]
    if save_residuals:
        out_specs.append(pl.BlockSpec((1, b, g), lambda t: (t, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((nt, b, g), jnp.float32))

    def kernel(xs_ref, wg_ref, ws_ref, mask_ref, hs_ref, *rest):
        if save_residuals:
            acts_ref, h_scr = rest
        else:
            (h_scr,), acts_ref = rest, None
        _fwd_kernel(xs_ref, wg_ref, ws_ref, mask_ref, hs_ref, acts_ref,
                    h_scr, d=d, save_residuals=save_residuals)

    outs = pl.pallas_call(
        kernel,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, b, g), lambda t: (t, 0, 0)),
            pl.BlockSpec((d, 2 * d), lambda t: (0, 0)),
            pl.BlockSpec((d, d), lambda t: (0, 0)),
            pl.BlockSpec((1, b, _LANES), lambda t: (t, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((b, d), jnp.float32)],
        interpret=interpret,
    )(xs, w_gate, w_state, mask)
    if save_residuals:
        return outs[0], outs[1]
    return outs[0], None


def _bwd(interpret, res, g_out):
    w_gate, w_state, mask, hs, acts = res
    dh_out = g_out
    xs_dtype = hs.dtype
    nt, b, d = dh_out.shape
    g = 3 * d

    dxs, dwg, dws = pl.pallas_call(
        functools.partial(_bwd_kernel, d=d, nt=nt),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, b, g), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((1, b, d),
                         lambda j: (jnp.maximum(nt - 2 - j, 0), 0, 0)),
            pl.BlockSpec((d, 2 * d), lambda j: (0, 0)),
            pl.BlockSpec((d, d), lambda j: (0, 0)),
            pl.BlockSpec((1, b, _LANES), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((1, b, d), lambda j: (nt - 1 - j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, b, g), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((d, 2 * d), lambda j: (0, 0)),
            pl.BlockSpec((d, d), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nt, b, g), xs_dtype),
            jax.ShapeDtypeStruct((d, 2 * d), jnp.float32),
            jax.ShapeDtypeStruct((d, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, d), jnp.float32),
            pltpu.VMEM((d, 2 * d), jnp.float32),
            pltpu.VMEM((d, d), jnp.float32),
        ],
        interpret=interpret,
    )(acts, hs, w_gate, w_state, mask, dh_out)
    return (dxs, dwg.astype(w_gate.dtype), dws.astype(w_state.dtype), None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused(xs, w_gate, w_state, mask, interpret):
    hs, _ = _fwd(xs, w_gate, w_state, mask, interpret, save_residuals=False)
    return hs


def _fused_fwd_rule(xs, w_gate, w_state, mask, interpret):
    hs, acts = _fwd(xs, w_gate, w_state, mask, interpret,
                    save_residuals=True)
    return hs, (w_gate, w_state, mask, hs, acts)


_fused.defvjp(_fused_fwd_rule, _bwd)


def vmem_bytes(b, d):
    """Backward-pass VMEM planning estimate: w_gate+w_state + their
    accumulators (6dd f32) + dh scratch + streamed per-step blocks."""
    resident = 6 * d * d + b * d
    streamed = 9 * b * d + _LANES * b
    return 4 * (resident + streamed)


def supported(b, d, act, gate_act, init_state):
    # reverse is handled by time-flipping in the caller (a reverse masked
    # scan over left-aligned ragged sequences == forward scan over the
    # time-flipped arrays, flipped back).  VMEM guard: see lstm.supported.
    from paddle_tpu.ops.pallas.common import vmem_budget_bytes
    return (act == "tanh" and gate_act == "sigmoid"
            and init_state is None
            and b % 8 == 0 and d % _LANES == 0
            and vmem_bytes(b, d) <= vmem_budget_bytes())


def gru_fused(xs_tm, mask_tm, w_gate, w_state, interpret=None):
    """Whole-sequence fused GRU.

    xs_tm: [T, B, 3D] time-major pre-projected [update|reset|candidate]
    inputs (bias included).  mask_tm: [T, B].  Returns (hs_tm, final h)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nt, b, g = xs_tm.shape
    mask_r = jnp.broadcast_to(
        mask_tm.astype(jnp.float32)[:, :, None], (nt, b, _LANES))
    hs = _fused(xs_tm, w_gate, w_state, mask_r, interpret)
    return hs, hs[-1]
