"""Shared Mosaic-layout helpers for the Pallas kernels.

Mosaic requires the last dimension of a block to be a multiple of the VPU
lane count (128) or the whole array dimension, so per-row statistics
(softmax running max/sum, sequence masks, saved lse) are stored
lane-REPLICATED in [rows, LANES] tiles and widened/narrowed with lanes().
"""

import jax.numpy as jnp

LANES = 128


def lanes(x, n):
    """[rows, LANES] lane-replicated -> [rows, n] (n <= LANES slices,
    multiples of LANES tile)."""
    if n == LANES:
        return x
    if n < LANES:
        return x[:, :n]
    return jnp.tile(x, (1, n // LANES))


def vmem_budget_bytes():
    """Per-core VMEM the kernels may plan against (~16 MB physically; 14 MB
    default leaves headroom for Mosaic's own buffers).  Override with
    PADDLE_TPU_KERNEL_VMEM_MB for chips with more (or to force the scan
    path by setting it tiny)."""
    import os
    return int(float(os.environ.get("PADDLE_TPU_KERNEL_VMEM_MB", "14"))
               * 1024 * 1024)
