"""Fused whole-sequence LSTM (Pallas) — the TPU-native answer to the
reference's fused CUDA LSTM kernels (cuda/src/hl_cuda_lstm.cu +
hl_lstm_ops.cuh:46-66, dispatched from LstmCompute).

Why a kernel when lax.scan works: XLA's scan round-trips the carry (h, c)
and the per-step gate tensor through HBM every timestep and re-fetches the
recurrent weights.  Here the grid IS the time loop (TPU grids execute
sequentially per core, the same property the flash-attention kernel uses):
w_r and the peephole vectors stay resident in VMEM across all T steps,
h/c live in VMEM scratch, and each step streams only its [B, 4D] gate
input in and its [B, D] output out.

Semantics match ops.rnn.lstm exactly (reference gate order
[a, in_gate, forget_gate, out_gate], peepholes on i/f from c_prev and on o
from c_new, masked steps freeze the carry): tests/test_pallas_lstm.py
proves forward+grad equality against the scan path.

Backward is a second time-reversed kernel (BPTT): recomputes nothing,
reads the forward-saved activations, accumulates dW_r in a VMEM f32
accumulator and the peephole/bias-free input grads as streamed outputs.
Per-batch peephole partials are reduced outside the kernel.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.common import LANES as _LANES, lanes as _lanes


def _fwd_kernel(xs_ref, wr_ref, chk_ref, mask_ref,
                hs_ref, cfin_ref, cs_ref, acts_ref, h_scr, c_scr,
                *, d, nt, save_residuals):
    """cs_ref/acts_ref are None in the lean (inference) variant — the
    residual tensors are ~5x the HBM traffic of the h output, so
    forward-only calls must not pay for them."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = jnp.zeros_like(h_scr)
        c_scr[:] = jnp.zeros_like(c_scr)

    h, c = h_scr[:], c_scr[:]
    x4 = xs_ref[0].astype(jnp.float32)
    wr = wr_ref[:].astype(jnp.float32)
    gates = x4 + jax.lax.dot_general(
        h, wr, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    a, ig, fg, og = (gates[:, 0:d], gates[:, d:2 * d],
                     gates[:, 2 * d:3 * d], gates[:, 3 * d:4 * d])
    ci, cf, co = chk_ref[0:1], chk_ref[1:2], chk_ref[2:3]   # [1, D]
    a = jnp.tanh(a)
    i = jax.nn.sigmoid(ig + c * ci)
    f = jax.nn.sigmoid(fg + c * cf)
    c_new = a * i + c * f
    o = jax.nn.sigmoid(og + c_new * co)
    h_new = o * jnp.tanh(c_new)

    m = _lanes(mask_ref[0], d)                               # [B, D] 0/1
    h = m * h_new + (1.0 - m) * h
    c = m * c_new + (1.0 - m) * c
    h_scr[:], c_scr[:] = h, c

    hs_ref[0] = h.astype(hs_ref.dtype)
    if save_residuals:
        cs_ref[0] = c.astype(cs_ref.dtype)
        acts_ref[0, :, 0:d] = a
        acts_ref[0, :, d:2 * d] = i
        acts_ref[0, :, 2 * d:3 * d] = f
        acts_ref[0, :, 3 * d:4 * d] = o

    @pl.when(t == nt - 1)
    def _():
        cfin_ref[0] = c_scr[:].astype(cfin_ref.dtype)


def _bwd_kernel(acts_ref, cs_ref, csp_ref, hsp_ref, wr_ref, chk_ref,
                mask_ref, dh_out_ref, dcfin_ref,
                dxs_ref, dwr_ref, dchk_ref,
                dh_scr, dc_scr, dwr_scr, dchk_scr, *, d, nt):
    j = pl.program_id(0)          # reversed: actual time t = nt - 1 - j
    t = nt - 1 - j

    @pl.when(j == 0)
    def _():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        # final-cell cotangent enters the chain at the last (first-reversed)
        # step, exactly where the scan's carry cotangent starts
        dc_scr[:] = dcfin_ref[0].astype(jnp.float32)
        dwr_scr[:] = jnp.zeros_like(dwr_scr)
        dchk_scr[:] = jnp.zeros_like(dchk_scr)

    a = acts_ref[0, :, 0:d]
    i = acts_ref[0, :, d:2 * d]
    f = acts_ref[0, :, 2 * d:3 * d]
    o = acts_ref[0, :, 3 * d:4 * d]
    c_t = cs_ref[0].astype(jnp.float32)
    zero_prev = (t == 0)
    c_prev = jnp.where(zero_prev, 0.0, csp_ref[0].astype(jnp.float32))
    h_prev = jnp.where(zero_prev, 0.0, hsp_ref[0].astype(jnp.float32))
    ci, cf, co = chk_ref[0:1], chk_ref[1:2], chk_ref[2:3]
    m = _lanes(mask_ref[0], d)

    dh = dh_scr[:] + dh_out_ref[0].astype(jnp.float32)
    dc_merged = dc_scr[:]
    tc = jnp.tanh(c_t)
    do_ = dh * tc
    dog = do_ * o * (1.0 - o)
    dc = dh * o * (1.0 - tc * tc) + dc_merged + dog * co
    da = dc * i
    di = dc * a
    dag = da * (1.0 - a * a)
    dig = di * i * (1.0 - i)
    dfg = dc * c_prev * f * (1.0 - f)
    # masked step: carry passes through untouched
    dgates = (jnp.concatenate([dag, dig, dfg, dog], axis=1)
              * _lanes(mask_ref[0], 4 * d))
    wr = wr_ref[:].astype(jnp.float32)
    dh_prev = jax.lax.dot_general(
        dgates, wr, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dc_prev = dc * f + dig * ci + dfg * cf

    # pass-through on masked steps carries the MERGED cotangents (the cell
    # terms in dc only exist on active steps)
    dh_scr[:] = m * dh_prev + (1.0 - m) * dh
    dc_scr[:] = m * dc_prev + (1.0 - m) * dc_merged
    dwr_scr[:] = dwr_scr[:] + jax.lax.dot_general(
        h_prev, dgates, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dchk_scr[:, 0:d] = dchk_scr[:, 0:d] + m * dig * c_prev
    dchk_scr[:, d:2 * d] = dchk_scr[:, d:2 * d] + m * dfg * c_prev
    dchk_scr[:, 2 * d:3 * d] = dchk_scr[:, 2 * d:3 * d] + m * dog * c_t

    dxs_ref[0] = dgates.astype(dxs_ref.dtype)

    @pl.when(j == nt - 1)
    def _():
        dwr_ref[:] = dwr_scr[:]
        dchk_ref[:] = dchk_scr[:]


def _fwd(xs, w_r, checks, mask, interpret, save_residuals):
    nt, b, g = xs.shape
    d = g // 4
    out_specs = [
        pl.BlockSpec((1, b, d), lambda t: (t, 0, 0)),      # hs
        pl.BlockSpec((1, b, d), lambda t: (0, 0, 0)),      # c_final
    ]
    out_shape = [
        jax.ShapeDtypeStruct((nt, b, d), xs.dtype),
        jax.ShapeDtypeStruct((1, b, d), jnp.float32),
    ]
    if save_residuals:
        out_specs += [
            pl.BlockSpec((1, b, d), lambda t: (t, 0, 0)),  # cs
            pl.BlockSpec((1, b, g), lambda t: (t, 0, 0)),  # acts
        ]
        out_shape += [
            jax.ShapeDtypeStruct((nt, b, d), jnp.float32),
            jax.ShapeDtypeStruct((nt, b, g), jnp.float32),
        ]

    def kernel(xs_ref, wr_ref, chk_ref, mask_ref, hs_ref, cfin_ref,
               *rest):
        if save_residuals:
            cs_ref, acts_ref, h_scr, c_scr = rest
        else:
            (h_scr, c_scr), cs_ref, acts_ref = rest, None, None
        _fwd_kernel(xs_ref, wr_ref, chk_ref, mask_ref, hs_ref, cfin_ref,
                    cs_ref, acts_ref, h_scr, c_scr,
                    d=d, nt=nt, save_residuals=save_residuals)

    outs = pl.pallas_call(
        kernel,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, b, g), lambda t: (t, 0, 0)),
            pl.BlockSpec((d, g), lambda t: (0, 0)),
            pl.BlockSpec((3, d), lambda t: (0, 0)),
            pl.BlockSpec((1, b, _LANES), lambda t: (t, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((b, d), jnp.float32),
            pltpu.VMEM((b, d), jnp.float32),
        ],
        interpret=interpret,
    )(xs, w_r, checks, mask)
    if save_residuals:
        hs, cfin, cs, acts = outs
        return hs, cfin, cs, acts
    hs, cfin = outs
    return hs, cfin, None, None


def _bwd(interpret, res, g_out):
    w_r, checks, mask, hs, cs, acts = res
    dh_out, dcfin = g_out
    xs_dtype = hs.dtype              # hs was emitted in xs.dtype
    nt, b, dd = dh_out.shape
    d = dd
    gcols = 4 * d

    dxs, dwr, dchk = pl.pallas_call(
        functools.partial(_bwd_kernel, d=d, nt=nt),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, b, gcols), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((1, b, d), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((1, b, d),
                         lambda j: (jnp.maximum(nt - 2 - j, 0), 0, 0)),
            pl.BlockSpec((1, b, d),
                         lambda j: (jnp.maximum(nt - 2 - j, 0), 0, 0)),
            pl.BlockSpec((d, gcols), lambda j: (0, 0)),
            pl.BlockSpec((3, d), lambda j: (0, 0)),
            pl.BlockSpec((1, b, _LANES), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((1, b, d), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((1, b, d), lambda j: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, b, gcols), lambda j: (nt - 1 - j, 0, 0)),
            pl.BlockSpec((d, gcols), lambda j: (0, 0)),
            pl.BlockSpec((b, 3 * d), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nt, b, gcols), xs_dtype),
            jax.ShapeDtypeStruct((d, gcols), jnp.float32),
            jax.ShapeDtypeStruct((b, 3 * d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, d), jnp.float32),
            pltpu.VMEM((b, d), jnp.float32),
            pltpu.VMEM((d, gcols), jnp.float32),
            pltpu.VMEM((b, 3 * d), jnp.float32),
        ],
        interpret=interpret,
    )(acts, cs, cs, hs, w_r, checks, mask, dh_out,
      dcfin.astype(jnp.float32))

    dchecks = dchk.sum(axis=0).reshape(3, d).astype(checks.dtype)
    return dxs, dwr.astype(w_r.dtype), dchecks, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused(xs, w_r, checks, mask, interpret):
    hs, cfin, _, _ = _fwd(xs, w_r, checks, mask, interpret,
                          save_residuals=False)
    return hs, cfin


def _fused_fwd_rule(xs, w_r, checks, mask, interpret):
    hs, cfin, cs, acts = _fwd(xs, w_r, checks, mask, interpret,
                              save_residuals=True)
    return (hs, cfin), (w_r, checks, mask, hs, cs, acts)


_fused.defvjp(_fused_fwd_rule, _bwd)


def vmem_bytes(b, d):
    """Planning estimate of the BACKWARD kernel's VMEM footprint (the
    larger pass): resident w_r + dW_r accumulator (4dd each, f32) +
    dh/dc/dchk scratch + one set of streamed per-step blocks (acts, cs,
    csp, hsp, dh_out, dxs, mask, dcfin).  docs/kernels.md carries the
    audit table derived from this."""
    resident = 8 * d * d + 3 * d + 5 * b * d        # weights+accum+scratch
    streamed = 13 * b * d + _LANES * b
    return 4 * (resident + streamed)


def supported(b, d, act, gate_act, state_act, init_state):
    """Kernel path preconditions; callers fall back to the scan otherwise.
    reverse is handled by the caller's time-flip (see rnn._fused_seq_apply).
    The VMEM guard keeps e.g. d=1280 (w_r alone = 26 MB f32) off the
    kernel path — it cannot be weight-resident on a ~16 MB core."""
    from paddle_tpu.ops.pallas.common import vmem_budget_bytes
    return (act == "tanh" and gate_act == "sigmoid" and state_act == "tanh"
            and init_state is None
            and b % 8 == 0 and d % _LANES == 0
            and vmem_bytes(b, d) <= vmem_budget_bytes())


def lstm_fused(xs_tm, mask_tm, w_r, check_i, check_f, check_o,
               interpret=None):
    """Whole-sequence fused LSTM.

    xs_tm: [T, B, 4D] time-major pre-projected gate inputs (bias included).
    mask_tm: [T, B] float 0/1.  Returns (hs_tm [T, B, D], final (h, c)).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nt, b, g = xs_tm.shape
    d = g // 4
    checks = jnp.stack([
        jnp.zeros((d,), jnp.float32) if v is None else v.astype(jnp.float32)
        for v in (check_i, check_f, check_o)])
    mask_r = jnp.broadcast_to(
        mask_tm.astype(jnp.float32)[:, :, None], (nt, b, _LANES))
    hs, cfin = _fused(xs_tm, w_r, checks, mask_r, interpret)
    return hs, (hs[-1], cfin[0].astype(hs.dtype))
