"""Gate-blocked fused LSTM forward (Pallas) for over-VMEM hidden sizes.

The resident-weight kernel (ops/pallas/lstm.py) needs w_r `[D, 4D]` in
VMEM for all T steps — impossible at d=1280 (26 MB f32 on a ~16 MB core;
docs/kernels.md audit).  This variant blocks the GATE dimension instead:

  grid = (T, D/blk), block-j innermost.  Each (t, j) step streams
  w_r[:, :, j] (`[D, 4, blk]`) from HBM — the same weight traffic as
  lax.scan — but the carried state stays in VMEM (h double-buffered A/B
  by t-parity so every block of step t reads the INTACT h_{t-1}; c is
  updated in place, its cell math being columnwise) and the whole cell
  fuses into the matmul.  What the scan pays per step and this kernel
  does not: h+c round-trips through HBM and separate elementwise ops.

The t-parity double buffer uses two STATIC scratch refs selected with
@pl.when (Mosaic cannot dynamically index a scratch ref's leading dim by
a traced value).  T is padded to even in the wrapper; the pad step gets
mask 0, which freezes the carry, so it is a no-op (same trick the ragged
path uses for short sequences).

Backward: pure-JAX BPTT over the forward-saved activations (a, i, f, o,
c per step) — no Pallas kernel and NO forward recompute; the two matmuls
per step (dgates @ w_r^T, h^T @ dgates) are exactly what XLA tiles well
at this size.  Saved-activation layout matches the resident kernel so
the scan oracle tests can share machinery.

Reference anchor: cuda/src/hl_cuda_lstm.cu (the fused production RNN
path this family replaces).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.common import LANES as _LANES, lanes as _lanes

_BLK = _LANES     # gate-column block width; 128 = one lane tile


def _cell_block(x4, h_prev, wblk, ci, cf, co, c_prev_blk):
    """One timestep's cell math for one gate-column block.  x4 [B,4,blk],
    h_prev [B,D] (full), wblk [D,4,blk].  Returns (a,i,f,o,c_new,h_new)
    for the block's columns."""
    r = jax.lax.dot_general(
        h_prev, wblk.reshape(wblk.shape[0], -1), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [B, 4*blk]
    blk = x4.shape[-1]
    g = x4.reshape(x4.shape[0], -1) + r                # [B, 4*blk]
    a = jnp.tanh(g[:, 0:blk])
    i = jax.nn.sigmoid(g[:, blk:2 * blk] + c_prev_blk * ci)
    f = jax.nn.sigmoid(g[:, 2 * blk:3 * blk] + c_prev_blk * cf)
    c_new = a * i + c_prev_blk * f
    o = jax.nn.sigmoid(g[:, 3 * blk:4 * blk] + c_new * co)
    h_new = o * jnp.tanh(c_new)
    return a, i, f, o, c_new, h_new


def _fwd_kernel(xs_ref, wr_ref, chk_ref, mask_ref,
                hs_ref, cfin_ref, cs_ref, acts_ref,
                ha_scr, hb_scr, c_scr, *, nt, save_residuals):
    t = pl.program_id(0)
    j = pl.program_id(1)
    blk = _BLK

    @pl.when((t == 0) & (j == 0))
    def _():
        ha_scr[:] = jnp.zeros_like(ha_scr)
        c_scr[:] = jnp.zeros_like(c_scr)

    x4 = xs_ref[0].astype(jnp.float32)                 # [B, 4, blk]
    wblk = wr_ref[:].astype(jnp.float32)               # [D, 4, blk]
    ci, cf, co = chk_ref[0:1], chk_ref[1:2], chk_ref[2:3]   # [1, blk]
    m = _lanes(mask_ref[0], blk)                       # [B, blk]
    c_prev = c_scr[:, pl.ds(j * blk, blk)]

    def run(prev_ref, new_ref):
        h_prev = prev_ref[:]                           # full [B, D]
        a, i, f, o, c_new, h_new = _cell_block(
            x4, h_prev, wblk, ci, cf, co, c_prev)
        # block read straight off the ref: Mosaic lowers dynamic slices
        # on REFS but not the dynamic_slice primitive on values
        h_prev_blk = prev_ref[:, pl.ds(j * blk, blk)]
        h_out = m * h_new + (1.0 - m) * h_prev_blk
        c_out = m * c_new + (1.0 - m) * c_prev
        new_ref[:, pl.ds(j * blk, blk)] = h_out
        c_scr[:, pl.ds(j * blk, blk)] = c_out
        hs_ref[0] = h_out.astype(hs_ref.dtype)
        if save_residuals:
            cs_ref[0] = c_out
            acts_ref[0, :, 0, :] = a
            acts_ref[0, :, 1, :] = i
            acts_ref[0, :, 2, :] = f
            acts_ref[0, :, 3, :] = o

    # static A/B selection by t-parity: even t reads A writes B, odd t
    # reads B writes A
    @pl.when(t % 2 == 0)
    def _():
        run(ha_scr, hb_scr)

    @pl.when(t % 2 == 1)
    def _():
        run(hb_scr, ha_scr)

    @pl.when(t == nt - 1)
    def _():
        cfin_ref[0] = c_scr[:, pl.ds(j * blk, blk)].astype(cfin_ref.dtype)


def _fwd(xs4, w_r4, checks, mask, interpret, save_residuals):
    nt, b, d = xs4.shape[0], xs4.shape[1], xs4.shape[3]
    nblk = d // _BLK

    out_specs = [
        pl.BlockSpec((1, b, _BLK), lambda t, j: (t, 0, j)),   # hs
        pl.BlockSpec((1, b, _BLK), lambda t, j: (0, 0, j)),   # c_final
    ]
    out_shape = [
        jax.ShapeDtypeStruct((nt, b, d), xs4.dtype),
        jax.ShapeDtypeStruct((1, b, d), jnp.float32),
    ]
    if save_residuals:
        out_specs += [
            pl.BlockSpec((1, b, _BLK), lambda t, j: (t, 0, j)),      # cs
            pl.BlockSpec((1, b, 4, _BLK), lambda t, j: (t, 0, 0, j)),  # acts
        ]
        out_shape += [
            jax.ShapeDtypeStruct((nt, b, d), jnp.float32),
            jax.ShapeDtypeStruct((nt, b, 4, d), jnp.float32),
        ]

    def kernel(xs_ref, wr_ref, chk_ref, mask_ref, hs_ref, cfin_ref, *rest):
        if save_residuals:
            cs_ref, acts_ref, ha, hb, c = rest
        else:
            (ha, hb, c), cs_ref, acts_ref = rest, None, None
        _fwd_kernel(xs_ref, wr_ref, chk_ref, mask_ref, hs_ref, cfin_ref,
                    cs_ref, acts_ref, ha, hb, c,
                    nt=nt, save_residuals=save_residuals)

    outs = pl.pallas_call(
        kernel,
        grid=(nt, nblk),
        in_specs=[
            pl.BlockSpec((1, b, 4, _BLK), lambda t, j: (t, 0, 0, j)),
            pl.BlockSpec((d, 4, _BLK), lambda t, j: (0, 0, j)),
            pl.BlockSpec((3, _BLK), lambda t, j: (0, j)),
            pl.BlockSpec((1, b, _LANES), lambda t, j: (t, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((b, d), jnp.float32),   # h parity buffer A
            pltpu.VMEM((b, d), jnp.float32),   # h parity buffer B
            pltpu.VMEM((b, d), jnp.float32),   # c (in-place per block)
        ],
        interpret=interpret,
    )(xs4, w_r4, checks, mask)
    if save_residuals:
        return outs
    return outs[0], outs[1], None, None


def _bwd_scan(res, g_out):
    """Saved-activation BPTT in plain JAX (reversed lax.scan): the
    recurrent matmuls XLA-tile fine at over-VMEM sizes; what the forward
    kernel bought (fused cell, VMEM carry) the backward buys back by not
    recomputing any activation."""
    w_r, checks, mask, hs, cs, acts = res
    dh_out, dcfin = g_out
    nt, b, d = dh_out.shape
    ci, cf, co = checks[0], checks[1], checks[2]
    wr = w_r.astype(jnp.float32)

    hs_prev = jnp.concatenate(
        [jnp.zeros_like(hs[:1]), hs[:-1]], axis=0).astype(jnp.float32)
    cs_prev = jnp.concatenate(
        [jnp.zeros_like(cs[:1]), cs[:-1]], axis=0)

    def step(carry, inp):
        dh_acc, dc_acc, dwr_acc, dchk_acc = carry
        a, i, f, o, c_t, c_prev, h_prev, m, dh_t = inp
        dh = dh_acc + dh_t.astype(jnp.float32)
        tc = jnp.tanh(c_t)
        dog = dh * tc * o * (1.0 - o)
        dc = dh * o * (1.0 - tc * tc) + dc_acc + dog * co
        dag = dc * i * (1.0 - a * a)
        dig = dc * a * i * (1.0 - i)
        dfg = dc * c_prev * f * (1.0 - f)
        dgates = jnp.concatenate([dag * m, dig * m, dfg * m, dog * m],
                                 axis=1)
        dh_prev = jax.lax.dot_general(
            dgates, wr, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dc_prev = dc * f + dig * ci + dfg * cf
        new_dh = m * dh_prev + (1.0 - m) * dh
        new_dc = m * dc_prev + (1.0 - m) * dc_acc
        dwr_acc = dwr_acc + jax.lax.dot_general(
            h_prev, dgates, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dchk_acc = dchk_acc + jnp.stack([
            jnp.sum(m * dig * c_prev, axis=0),
            jnp.sum(m * dfg * c_prev, axis=0),
            jnp.sum(m * dog * c_t, axis=0)])
        return (new_dh, new_dc, dwr_acc, dchk_acc), dgates

    m_t = mask[:, :, :1]                      # [T, B, 1] lane 0
    m_full = jnp.broadcast_to(m_t, (nt, b, d))
    init = (jnp.zeros((b, d), jnp.float32),
            dcfin[0].astype(jnp.float32),
            jnp.zeros((d, 4 * d), jnp.float32),
            jnp.zeros((3, d), jnp.float32))
    acts_flat = acts.reshape(nt, b, 4, d)
    (dh0, dc0, dwr, dchk), dxs = jax.lax.scan(
        step, init,
        (acts_flat[:, :, 0], acts_flat[:, :, 1], acts_flat[:, :, 2],
         acts_flat[:, :, 3], cs.astype(jnp.float32), cs_prev, hs_prev,
         m_full, dh_out),
        reverse=True)
    return (dxs.astype(hs.dtype), dwr.astype(w_r.dtype),
            dchk.astype(checks.dtype), None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused(xs4, w_r4, checks, mask, interpret):
    hs, cfin, _, _ = _fwd(xs4, w_r4, checks, mask, interpret,
                          save_residuals=False)
    return hs, cfin


def _fused_fwd_rule(xs4, w_r4, checks, mask, interpret):
    hs, cfin, cs, acts = _fwd(xs4, w_r4, checks, mask, interpret,
                              save_residuals=True)
    d = xs4.shape[3]
    w_r = w_r4.reshape(w_r4.shape[0], 4 * d)
    return (hs, cfin), (w_r, checks, mask, hs, cs, acts)


def _fused_bwd_rule(interpret, res, g_out):
    dxs, dwr, dchk, _ = _bwd_scan(res, g_out)
    nt, b, d = dxs.shape[0], dxs.shape[1], dxs.shape[2] // 4
    return (dxs.reshape(nt, b, 4, d), dwr.reshape(dwr.shape[0], 4, d),
            dchk, None)


_fused.defvjp(_fused_fwd_rule, _fused_bwd_rule)


def vmem_bytes(b, d):
    """Training-path footprint (the larger, save_residuals forward): three
    [B, D] f32 carry scratches + two pipelined weight blocks [D, 4, 128]
    + double-buffered streamed blocks INCLUDING the residual outputs
    (cs [B, 128] + acts [B, 4, 128]) the VJP variant emits."""
    resident = 3 * b * d + 2 * d * 4 * _BLK
    streamed = 2 * (b * 4 * _BLK + b * _LANES + 2 * b * _BLK
                    + b * _BLK + b * 4 * _BLK)
    return 4 * (resident + streamed)


def supported(b, d, act, gate_act, state_act, init_state):
    from paddle_tpu.ops.pallas.common import vmem_budget_bytes
    return (act == "tanh" and gate_act == "sigmoid" and state_act == "tanh"
            and init_state is None
            and b % 8 == 0 and d % _BLK == 0
            and vmem_bytes(b, d) <= vmem_budget_bytes())


def lstm_fused_blocked(xs_tm, mask_tm, w_r, check_i, check_f, check_o,
                       interpret=None):
    """Whole-sequence gate-blocked LSTM; same contract as
    lstm.lstm_fused: xs_tm [T, B, 4D] pre-projected gate inputs, mask
    [T, B] -> (hs_tm [T, B, D], final (h, c))."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nt, b, g = xs_tm.shape
    d = g // 4
    checks = jnp.stack([
        jnp.zeros((d,), jnp.float32) if v is None else v.astype(jnp.float32)
        for v in (check_i, check_f, check_o)])
    # pad T to even for the parity double-buffer; the pad step's mask is 0,
    # which freezes the carry (a no-op step)
    pad = nt % 2
    if pad:
        xs_tm = jnp.concatenate(
            [xs_tm, jnp.zeros_like(xs_tm[:1])], axis=0)
        mask_tm = jnp.concatenate(
            [mask_tm, jnp.zeros_like(mask_tm[:1])], axis=0)
    ntp = nt + pad
    xs4 = xs_tm.reshape(ntp, b, 4, d)
    w_r4 = w_r.reshape(d, 4, d)
    mask_r = jnp.broadcast_to(
        mask_tm.astype(jnp.float32)[:, :, None], (ntp, b, _LANES))
    hs, cfin = _fused(xs4, w_r4, checks, mask_r, interpret)
    hs = hs[:nt]
    return hs, (hs[-1], cfin[0].astype(hs.dtype))
