"""Fused decode-attention kernels (Pallas TPU): read the KV cache ONCE
per step.

The serving decode hot path is memory-bound on every analytic family
(BENCH_ANALYTIC_r06.json), so bytes — not FLOPs — set the step time.
The reference XLA paths in ``models/transformer`` pay for the KV cache
more than once per layer per step:

* slab (``_cached_self_attn_slots``): ``repeat_kv_heads`` widens the
  grouped K/V to full head width and the dense attention materializes
  the ``[S, H, T]`` score matrix in HBM before the softmax reads it
  back;
* paged (``_cached_self_attn_paged``): the per-row chain gather
  ``pool[tables]`` copies every row's blocks into a contiguous
  ``[S, T, Dkv]`` HBM buffer — a second full read AND a full write of
  the logical cache — before the same widened-score dance.

The two kernels here delete all of that traffic.  Per row the K/V
stripe streams HBM -> VMEM exactly once; the masked online softmax
(flash-style running max/sum, the ``flash_attention.py`` recipe) and
the grouped-KV -> full-head expansion happen in VMEM/registers; neither
the score matrix nor a second KV copy ever exists in HBM.

* ``decode_attention_slab``: grid ``(S, T/blk)`` with the kv dimension
  innermost; per-row ``positions`` ride as SCALAR-PREFETCH data
  (``pltpu.PrefetchScalarGridSpec``) so the k-block index map CLAMPS at
  the row's position — blocks past a row's live prefix map to the same
  block id, which the Pallas pipeline recognizes and never re-fetches.

* ``decode_attention_paged``: the per-slot block TABLE is the second
  scalar-prefetch operand and the kernel walks it directly — the
  ``[1, block_size, Dkv]`` k/v specs index ``pool[tables[r, j]]``, so a
  row reads ONLY the physical blocks it owns (clamped at its position,
  like the slab) and the chain gather disappears from the HLO entirely
  (perf/analytic.py's fusion-proof gate pins exactly that).

Masking matches ``_attend`` exactly: cols > positions[r] sit at -1e30,
whose exp is 0.0 — cache width beyond a row's position never perturbs
its numerics, so greedy streams through the kernels stay token-for-token
identical to ``lm_generate`` (tests/test_pallas_decode.py pins it across
admission/eviction/CoW churn and supervisor recovery).

INT8 K/V (quant/kv.py; docs/serving.md "Quantized serving"): every
kernel takes optional ``kscale``/``vscale`` per-(position, head) f32
sidecars marking a quantized cache.  The sidecar blocks ride the SAME
clamped/table-walked DMA stream as the int8 K/V blocks, and the
widening happens in REGISTERS inside ``_accumulate`` (one broadcast
multiply per KV-head group panel) — int8 is what streams from HBM and
the widened K/V never exists in any memory.  ``kernel_cost`` declares
the honest int8 byte counts (1-byte elements + the f32 sidecar).

Dispatch: callers go through ``maybe_slab`` / ``maybe_paged``, which
return None (caller falls back to the reference XLA path) unless the
``pallas_decode`` flag enables the kernels — ``auto`` follows
``use_pallas()`` (TPU only; the CPU tier-1 default stays the reference
path, preserving the greedy bit-identity discipline), ``always`` forces
them anywhere (interpret mode off-TPU — the CPU test/smoke mode), ``off``
disables.  The flag is read at TRACE time: set it before constructing
the engine/jitting the step.
"""

import contextlib
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.common import LANES as _LANES, lanes as _lanes

_NEG = -1e30

# test/bench override for the pallas_decode flag: None = read FLAGS
# (utils/flags.py), else one of "auto" | "always" | "off" — same values
# the flag takes.  The FUSED_LSTM pattern (ops/rnn.py).
MODE = None


def _mode():
    if MODE is not None:
        return MODE
    from paddle_tpu.utils.flags import FLAGS
    return getattr(FLAGS, "pallas_decode", "auto")


@contextlib.contextmanager
def forced_mode(mode):
    """Temporarily force the kernel dispatch mode ("always" | "off" |
    "auto") — tests and the A/B bench.  The mode is read at TRACE time,
    so wrap the jit/lower call, not just the execution."""
    global MODE
    old = MODE
    MODE = mode
    try:
        yield
    finally:
        MODE = old


def decode_kernels_enabled():
    """True when the fused decode kernels should serve the slot/paged
    steps (read at trace time by ``models/transformer``)."""
    m = str(_mode()).lower()
    if m in ("0", "off", "false", "no"):
        return False
    if m in ("1", "on", "always", "true", "yes"):
        return True
    if m != "auto":
        raise ValueError(f"pallas_decode={m!r} (takes auto | always | off)")
    from paddle_tpu.ops import pallas as pk
    return pk.use_pallas()


def _block_k_cap():
    from paddle_tpu.utils.flags import FLAGS
    return int(getattr(FLAGS, "pallas_decode_block_k", 512))


def _interpret(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _head_split(d, dkv, num_heads):
    """(dh, hkv, group) from the projection widths, or None when the
    widths don't describe a grouped-head layout the kernels handle."""
    if num_heads < 1 or d % num_heads:
        return None
    dh = d // num_heads
    if dh < 1 or dkv % dh:
        return None
    hkv = dkv // dh
    if hkv < 1 or num_heads % hkv:
        return None
    return dh, hkv, num_heads // hkv


def _lane_tileable(n):
    """common.lanes() can slice (n <= LANES) or tile (n % LANES == 0)."""
    return n <= _LANES or n % _LANES == 0


def _pick_block_k(t, cap, interpret, quant=False):
    """Largest k-tile <= cap dividing the slab length, compatible with
    the lane-replicated running-stat layout (<= LANES or a LANES
    multiple).  Single-block (blk == t) when the whole stripe fits the
    cap — the common serving shape, where the online softmax degenerates
    to one plain masked softmax.  Compiled mode additionally wants
    8-sublane-divisible tiles — 32 for int8 K/V (``quant``; the s8 VMEM
    tile is (32, 128)), applied HERE so a 32-divisible tile is found
    whenever one exists rather than the largest-divisor pick being
    rejected downstream; interpret mode takes any shape."""
    if t < 1:
        return None
    sublane = 32 if quant else 8
    b = min(t, cap)
    while b >= 1:
        if t % b == 0 and _lane_tileable(b) \
                and (interpret or b % sublane == 0):
            return b
        b -= 1
    return None


def _mosaic_ok(blk, dkv, dh, interpret, quant=False):
    """Tiling constraints.  The lane-replicated running stats require a
    lane-tileable k-tile AND head dim in EVERY mode — ``_lanes`` can
    only slice (n <= LANES) or tile (n % LANES == 0), so e.g. a paged
    block_size of 136 must fall back to the reference path rather than
    fail mid-trace.  Compiled mode additionally wants 8-divisible
    sublane tiles and a lane-tileable Dkv; int8 K/V (``quant``) raises
    the sublane requirement to 32 — the s8 VMEM tile is (32, 128)."""
    if not (_lane_tileable(blk) and _lane_tileable(dh)):
        return False
    if interpret:
        return True
    if quant and blk % 32:
        return False
    return blk % 8 == 0 and _lane_tileable(dkv)


# ------------------------------------------------------------ kernel body

def _accumulate(q, kb, vb, col0, blk, pos, m_scr, l_scr, acc_scr, *,
                num_heads, hkv, dh, scale, sl=slice(None), ks=None,
                vs=None):
    """One K/V block of the masked online softmax for one query lane.

    q: [H, dh] f32; kb/vb: [blk, Dkv] f32; col0: first global column of
    this block; pos: the LANE's position (cols > pos masked to -1e30).
    Grouped KV expands in REGISTERS: each kv head's [dh]-slice meets its
    query group's rows — no widened K/V ever exists in memory.  ``sl``
    selects this lane's running-stat rows inside scratch shaped
    [K*H, ...] (the Tq=chunk kernels; Tq=1 passes the whole scratch).
    A block entirely past ``pos`` is a BIT-EXACT no-op: every score
    masks to -1e30, so p underflows to exactly 0.0 and alpha is exactly
    1.0 — the chunk kernels rely on this for their shorter lanes.

    ks/vs: [blk, Hkv] f32 per-(position, head) scale panels for int8
    K/V (quant/kv.py): the caller hands kb/vb already CONVERTED s8 ->
    f32 and the per-head scale multiplies each group's panel here — the
    in-register dequant; the widened stripe never exists in memory,
    int8 is what streamed from HBM."""
    group = num_heads // hkv
    parts = []
    for g in range(hkv):
        qg = q[g * group:(g + 1) * group]              # [group, dh]
        kg = kb[:, g * dh:(g + 1) * dh]                # [blk, dh]
        if ks is not None:
            kg = kg * ks[:, g:g + 1]
        parts.append(jax.lax.dot_general(
            qg, kg, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32))       # [group, blk]
    s = (jnp.concatenate(parts, axis=0) if hkv > 1 else parts[0]) * scale
    cols = jax.lax.broadcasted_iota(jnp.int32, (num_heads, blk), 1) + col0
    s = jnp.where(cols <= pos, s, _NEG)
    m_prev, l_prev = m_scr[sl], l_scr[sl]              # [H, LANES]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - _lanes(m_new, blk))
    alpha = jnp.exp(m_prev - m_new)
    m_scr[sl] = m_new
    l_scr[sl] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    parts = []
    for g in range(hkv):
        pg = p[g * group:(g + 1) * group]              # [group, blk]
        vg = vb[:, g * dh:(g + 1) * dh]                # [blk, dh]
        if vs is not None:
            vg = vg * vs[:, g:g + 1]
        parts.append(jax.lax.dot_general(
            pg, vg, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32))       # [group, dh]
    av = jnp.concatenate(parts, axis=0) if hkv > 1 else parts[0]
    acc_scr[sl] = acc_scr[sl] * _lanes(alpha, dh) + av


def kernel_cost(s, t_span, d, dkv, itemsize=4, tq=1, kv_itemsize=None,
                scale_hkv=0):
    """The kernel's declared traffic/compute — the ``pl.CostEstimate``
    handed to Mosaic, and the number a TPU cost model reports for the
    fused custom call.  Bytes are the whole point: q in + out + each
    row's K AND V stripe read ONCE (worst case — the clamped index maps
    stop at each row's position, so the real stream is shorter), plus
    the scalar operands.  No score matrix, no second KV copy.  ``tq``:
    query lanes per row (1 = plain decode; K = the chunked-prefill
    step — the KV stream is UNCHANGED, every lane consumes it in
    VMEM).  ``kv_itemsize``/``scale_hkv``: the honest int8 accounting —
    1-byte K/V elements plus the f32 per-(position, head) scale sidecar
    (2 * s * t_span * scale_hkv * 4 bytes); 0 = no sidecar."""
    kv_itemsize = itemsize if kv_itemsize is None else kv_itemsize
    kv_bytes = 2 * s * t_span * dkv * kv_itemsize \
        + 2 * s * t_span * scale_hkv * 4
    io_bytes = 2 * s * tq * d * itemsize + s * tq * 4  # + int32 positions
    #           (the paged block table adds s * nb_row * 4 more — noise)
    heads_flops = 2 * 2 * s * tq * t_span * d   # qk^T + p@v
    return pl.CostEstimate(flops=heads_flops,
                           bytes_accessed=kv_bytes + io_bytes,
                           transcendentals=s * tq * t_span)


def _init_row(m_scr, l_scr, acc_scr):
    m_scr[:] = jnp.full_like(m_scr, _NEG)
    l_scr[:] = jnp.zeros_like(l_scr)
    acc_scr[:] = jnp.zeros_like(acc_scr)


def _finalize(o_ref, l_scr, acc_scr, dh):
    l = jnp.maximum(l_scr[:], 1e-30)
    o_ref[0] = (acc_scr[:] / _lanes(l, dh)).astype(o_ref.dtype)


def _slab_kernel(pos_ref, q_ref, k_ref, v_ref, *rest, blk, num_heads,
                 hkv, dh, scale):
    # int8 K/V adds two scale-sidecar operands between v and the output
    # (quantized dispatch appends their BlockSpecs); the f32 layout is
    # unchanged
    if len(rest) == 6:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest
    r = pl.program_id(0)
    j = pl.program_id(1)
    pos = pos_ref[r]

    @pl.when(j == 0)
    def _():
        _init_row(m_scr, l_scr, acc_scr)

    @pl.when(j * blk <= pos)
    def _():
        _accumulate(q_ref[0].astype(jnp.float32),
                    k_ref[0].astype(jnp.float32),
                    v_ref[0].astype(jnp.float32),
                    j * blk, blk, pos, m_scr, l_scr, acc_scr,
                    num_heads=num_heads, hkv=hkv, dh=dh, scale=scale,
                    ks=None if ks_ref is None else ks_ref[0],
                    vs=None if vs_ref is None else vs_ref[0])

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        _finalize(o_ref, l_scr, acc_scr, dh)


def _paged_kernel(pos_ref, tbl_ref, *args, **kw):
    """Same body as the slab kernel — the block table shapes the DMA
    stream through the index maps, not the compute; ``tbl_ref`` is
    consumed entirely by the BlockSpecs."""
    del tbl_ref
    _slab_kernel(pos_ref, *args, **kw)


def _chunk_kernel(pos_ref, q_ref, k_ref, v_ref, *rest, blk, kk,
                  num_heads, hkv, dh, scale):
    """Tq=chunk body: ``kk`` query lanes per row share each streamed K/V
    block.  pos_ref [S, K] carries every lane's own position (the
    engine's clamped ``qpos`` — non-decreasing per row, inactive lanes
    repeat the last active lane's), so lane i's mask is causal within
    the chunk AND clamped at the row's live prefix.  Lane stats live in
    [K*H, .]-shaped scratch, sliced per lane; the K/V stripe is read
    from HBM exactly once per row — the chunk consumes it in VMEM (and
    for int8 K/V every lane shares the same in-register dequant panels:
    the scale sidecars ride the same block stream)."""
    if len(rest) == 6:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest
    r = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        _init_row(m_scr, l_scr, acc_scr)

    # the row's furthest lane gates the block (per-lane masking inside
    # _accumulate makes an out-of-range lane's visit a bit-exact no-op)
    @pl.when(j * blk <= pos_ref[r, kk - 1])
    def _():
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        ks = None if ks_ref is None else ks_ref[0]
        vs = None if vs_ref is None else vs_ref[0]
        def _lane(i, sl):
            _accumulate(q_ref[0, sl].astype(jnp.float32), kb, vb,
                        j * blk, blk, pos_ref[r, i], m_scr, l_scr,
                        acc_scr, num_heads=num_heads, hkv=hkv, dh=dh,
                        scale=scale, sl=sl, ks=ks, vs=vs)

        _lane(0, slice(0, num_heads))    # lane 0 is always live

        # the decode-row fast path: live lanes have strictly increasing
        # positions and an inactive lane REPEATS the last live lane's
        # clamped qpos (engine ``_chunk_lanes``), so last == first means
        # the row has exactly ONE live lane — a plain decode row riding
        # the chunk step — and every other lane's accumulate is skipped
        # (their scratch keeps the _init_row zeros; _finalize's
        # max(l, eps) emits deterministic zeros nothing reads).  The
        # predicate is pos DATA — no retrace — and ONE conditional per
        # kernel keeps the step's HLO structurally flat for the
        # analytic-diff gate; partially-live rows (chunk-ingest tails,
        # spec verify) still visit every lane, where per-lane masking
        # makes the dead visits bit-exact no-ops.
        @pl.when(pos_ref[r, kk - 1] != pos_ref[r, 0])
        def _():
            for i in range(1, kk):
                _lane(i, slice(i * num_heads, (i + 1) * num_heads))

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        _finalize(o_ref, l_scr, acc_scr, dh)


def _paged_chunk_kernel(pos_ref, tbl_ref, *args, **kw):
    del tbl_ref
    _chunk_kernel(pos_ref, *args, **kw)


# ------------------------------------------------------------ public API

def _check_scales(name, kscale, vscale, lead_shape, hkv):
    """Validate the int8 scale sidecars (both or neither; shapes match
    the K/V buffers with Hkv trailing).  Returns True when quantized."""
    if kscale is None and vscale is None:
        return False
    if kscale is None or vscale is None:
        raise ValueError(f"{name}: kscale and vscale come together")
    want = lead_shape + (hkv,)
    if tuple(kscale.shape) != want or tuple(vscale.shape) != want:
        raise ValueError(
            f"{name}: scale sidecars must be {want}, got "
            f"{kscale.shape}/{vscale.shape}")
    return True


def decode_attention_slab(q, k, v, positions, num_heads, *, block_k=None,
                          interpret=None, kscale=None, vscale=None):
    """Fused slab decode attention: q [S, D], k/v [S, T, Dkv] (the
    already-updated cache), positions [S] int32 -> [S, D].  Row r
    attends its own stripe at cols <= positions[r]; the stripe is read
    from HBM exactly once and no score matrix is ever materialized.
    kscale/vscale [S, T, Hkv] f32 mark an INT8 cache (quant/kv.py): the
    kernel DMAs the int8 stripe + its scale sidecar and widens in
    registers inside the accumulator — the widened K/V never exists in
    any memory.  Raises ValueError on shapes the kernel doesn't cover —
    callers use ``maybe_slab``."""
    interpret = _interpret(interpret)
    s, d = q.shape
    t, dkv = k.shape[1], k.shape[2]
    split = _head_split(d, dkv, num_heads)
    blk = _pick_block_k(t, block_k or _block_k_cap(), interpret,
                        quant=kscale is not None)
    if split is None or blk is None:
        raise ValueError(
            f"decode_attention_slab: unsupported shape q={q.shape} "
            f"k={k.shape} heads={num_heads}")
    dh, hkv, _group = split
    quant = _check_scales("decode_attention_slab", kscale, vscale,
                          (s, t), hkv)
    if not _mosaic_ok(blk, dkv, dh, interpret, quant=quant):
        raise ValueError(
            f"decode_attention_slab: untileable blk={blk} dkv={dkv} "
            f"dh={dh} for the compiled backend")
    scale = 1.0 / math.sqrt(dh)
    kernel = functools.partial(_slab_kernel, blk=blk, num_heads=num_heads,
                               hkv=hkv, dh=dh, scale=scale)
    # clamp at the row's live prefix: blocks past positions[r] re-map
    # to the last needed block — same index, no re-fetch
    kv_map = lambda r, j, pos: (r, jnp.minimum(j, pos[r] // blk), 0)
    in_specs = [
        pl.BlockSpec((1, num_heads, dh), lambda r, j, pos: (r, 0, 0)),
        pl.BlockSpec((1, blk, dkv), kv_map),
        pl.BlockSpec((1, blk, dkv), kv_map),
    ]
    operands = [q.reshape(s, num_heads, dh), k, v]
    if quant:
        in_specs += [pl.BlockSpec((1, blk, hkv), kv_map),
                     pl.BlockSpec((1, blk, hkv), kv_map)]
        operands += [kscale, vscale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s, t // blk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, num_heads, dh),
                               lambda r, j, pos: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((num_heads, _LANES), jnp.float32),
            pltpu.VMEM((num_heads, _LANES), jnp.float32),
            pltpu.VMEM((num_heads, dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, num_heads, dh), q.dtype),
        cost_estimate=kernel_cost(
            s, t, d, dkv, q.dtype.itemsize,
            kv_itemsize=k.dtype.itemsize,
            scale_hkv=hkv if quant else 0),
        interpret=interpret,
    )(jnp.asarray(positions, jnp.int32), *operands)
    return out.reshape(s, d)


def decode_attention_paged(q, k, v, positions, tables, num_heads, *,
                           interpret=None, kscale=None, vscale=None):
    """Fused paged decode attention: q [S, D], k/v [num_blocks,
    block_size, Dkv] (the shared block POOL, already scatter-updated),
    positions [S] int32, tables [S, blocks_per_row] int32 -> [S, D].

    The block table is the kernel's second scalar-prefetch operand: the
    k/v index maps read ``tables[r, j]`` directly, so row r's DMA stream
    is exactly the physical blocks it owns (clamped at its position) —
    the ``pool[tables]`` chain gather and its [S, T, Dkv] HBM buffer
    are gone, not fused.  kscale/vscale [num_blocks, block_size, Hkv]
    f32 mark an INT8 pool (quant/kv.py): the sidecar blocks ride the
    SAME table-walked stream and the widening happens in registers.
    Raises ValueError on shapes the kernel doesn't cover — callers use
    ``maybe_paged``."""
    interpret = _interpret(interpret)
    s, d = q.shape
    bs, dkv = k.shape[1], k.shape[2]
    nb_row = tables.shape[1]
    split = _head_split(d, dkv, num_heads)
    if split is None:
        raise ValueError(
            f"decode_attention_paged: unsupported shape q={q.shape} "
            f"pool={k.shape} heads={num_heads}")
    dh, hkv, _group = split
    quant = _check_scales("decode_attention_paged", kscale, vscale,
                          (k.shape[0], bs), hkv)
    if not _mosaic_ok(bs, dkv, dh, interpret, quant=quant):
        raise ValueError(
            f"decode_attention_paged: untileable block_size={bs} "
            f"dkv={dkv} dh={dh} for the compiled backend")
    scale = 1.0 / math.sqrt(dh)
    kernel = functools.partial(_paged_kernel, blk=bs,
                               num_heads=num_heads, hkv=hkv, dh=dh,
                               scale=scale)

    def _kv_map(r, j, pos, tbl):
        # walk the row's chain, clamped at its live prefix: entries past
        # positions[r] (scratch/stale ids) are never even addressed
        return (tbl[r, jnp.minimum(j, pos[r] // bs)], 0, 0)

    in_specs = [
        pl.BlockSpec((1, num_heads, dh),
                     lambda r, j, pos, tbl: (r, 0, 0)),
        pl.BlockSpec((1, bs, dkv), _kv_map),
        pl.BlockSpec((1, bs, dkv), _kv_map),
    ]
    operands = [q.reshape(s, num_heads, dh), k, v]
    if quant:
        in_specs += [pl.BlockSpec((1, bs, hkv), _kv_map),
                     pl.BlockSpec((1, bs, hkv), _kv_map)]
        operands += [kscale, vscale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, nb_row),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, num_heads, dh),
                               lambda r, j, pos, tbl: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((num_heads, _LANES), jnp.float32),
            pltpu.VMEM((num_heads, _LANES), jnp.float32),
            pltpu.VMEM((num_heads, dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, num_heads, dh), q.dtype),
        cost_estimate=kernel_cost(
            s, nb_row * bs, d, dkv, q.dtype.itemsize,
            kv_itemsize=k.dtype.itemsize,
            scale_hkv=hkv if quant else 0),
        interpret=interpret,
    )(jnp.asarray(positions, jnp.int32),
      jnp.asarray(tables, jnp.int32), *operands)
    return out.reshape(s, d)


def decode_attention_slab_chunk(q, k, v, qpos, num_heads, *,
                                block_k=None, interpret=None,
                                kscale=None, vscale=None):
    """Fused Tq=chunk slab decode attention (the unified chunked-prefill
    step): q [S, K, D], k/v [S, T, Dkv] (the already-updated cache),
    qpos [S, K] int32 per-LANE positions (non-decreasing per row; the
    engine clamps inactive lanes to the last active one) -> [S, K, D].
    Lane (r, i) attends row r's stripe at cols <= qpos[r, i]; the
    stripe streams HBM -> VMEM once per row and every lane consumes it
    there — no [S, K, T] score matrix.  kscale/vscale [S, T, Hkv] f32
    mark an INT8 cache — in-register dequant, every lane sharing the
    widened panels.  Raises ValueError on shapes the kernel doesn't
    cover — callers use ``maybe_slab_chunk``."""
    interpret = _interpret(interpret)
    s, kk, d = q.shape
    t, dkv = k.shape[1], k.shape[2]
    split = _head_split(d, dkv, num_heads)
    blk = _pick_block_k(t, block_k or _block_k_cap(), interpret,
                        quant=kscale is not None)
    if split is None or blk is None or not _chunk_ok(kk, num_heads,
                                                    interpret):
        raise ValueError(
            f"decode_attention_slab_chunk: unsupported shape q={q.shape} "
            f"k={k.shape} heads={num_heads}")
    dh, hkv, _group = split
    quant = _check_scales("decode_attention_slab_chunk", kscale, vscale,
                          (s, t), hkv)
    if not _mosaic_ok(blk, dkv, dh, interpret, quant=quant):
        raise ValueError(
            f"decode_attention_slab_chunk: untileable blk={blk} "
            f"dkv={dkv} dh={dh} for the compiled backend")
    scale = 1.0 / math.sqrt(dh)
    kernel = functools.partial(_chunk_kernel, blk=blk, kk=kk,
                               num_heads=num_heads, hkv=hkv, dh=dh,
                               scale=scale)
    # clamp at the row's FURTHEST lane: blocks past it re-map to the
    # last needed block — same index, no re-fetch
    kv_map = lambda r, j, pos: (
        r, jnp.minimum(j, pos[r, kk - 1] // blk), 0)
    in_specs = [
        pl.BlockSpec((1, kk * num_heads, dh),
                     lambda r, j, pos: (r, 0, 0)),
        pl.BlockSpec((1, blk, dkv), kv_map),
        pl.BlockSpec((1, blk, dkv), kv_map),
    ]
    operands = [q.reshape(s, kk * num_heads, dh), k, v]
    if quant:
        in_specs += [pl.BlockSpec((1, blk, hkv), kv_map),
                     pl.BlockSpec((1, blk, hkv), kv_map)]
        operands += [kscale, vscale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s, t // blk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, kk * num_heads, dh),
                               lambda r, j, pos: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kk * num_heads, _LANES), jnp.float32),
            pltpu.VMEM((kk * num_heads, _LANES), jnp.float32),
            pltpu.VMEM((kk * num_heads, dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, kk * num_heads, dh), q.dtype),
        cost_estimate=kernel_cost(
            s, t, d, dkv, q.dtype.itemsize, tq=kk,
            kv_itemsize=k.dtype.itemsize,
            scale_hkv=hkv if quant else 0),
        interpret=interpret,
    )(jnp.asarray(qpos, jnp.int32), *operands)
    return out.reshape(s, kk, d)


def decode_attention_paged_chunk(q, k, v, qpos, tables, num_heads, *,
                                 interpret=None, kscale=None,
                                 vscale=None):
    """Fused Tq=chunk PAGED decode attention: q [S, K, D], k/v
    [num_blocks, block_size, Dkv] (the shared pool, already
    scatter-updated for the whole chunk span), qpos [S, K], tables
    [S, blocks_per_row] int32 -> [S, K, D].  The block table stays the
    second scalar-prefetch operand: a row's DMA stream is exactly the
    physical blocks it owns, clamped at its furthest lane.  kscale/
    vscale [num_blocks, block_size, Hkv] f32 mark an INT8 pool —
    sidecar blocks ride the same stream, dequant in registers."""
    interpret = _interpret(interpret)
    s, kk, d = q.shape
    bs, dkv = k.shape[1], k.shape[2]
    nb_row = tables.shape[1]
    split = _head_split(d, dkv, num_heads)
    if split is None or not _chunk_ok(kk, num_heads, interpret):
        raise ValueError(
            f"decode_attention_paged_chunk: unsupported shape "
            f"q={q.shape} pool={k.shape} heads={num_heads}")
    dh, hkv, _group = split
    quant = _check_scales("decode_attention_paged_chunk", kscale,
                          vscale, (k.shape[0], bs), hkv)
    if not _mosaic_ok(bs, dkv, dh, interpret, quant=quant):
        raise ValueError(
            f"decode_attention_paged_chunk: untileable block_size={bs} "
            f"dkv={dkv} dh={dh} for the compiled backend")
    scale = 1.0 / math.sqrt(dh)
    kernel = functools.partial(_paged_chunk_kernel, blk=bs, kk=kk,
                               num_heads=num_heads, hkv=hkv, dh=dh,
                               scale=scale)

    def _kv_map(r, j, pos, tbl):
        return (tbl[r, jnp.minimum(j, pos[r, kk - 1] // bs)], 0, 0)

    in_specs = [
        pl.BlockSpec((1, kk * num_heads, dh),
                     lambda r, j, pos, tbl: (r, 0, 0)),
        pl.BlockSpec((1, bs, dkv), _kv_map),
        pl.BlockSpec((1, bs, dkv), _kv_map),
    ]
    operands = [q.reshape(s, kk * num_heads, dh), k, v]
    if quant:
        in_specs += [pl.BlockSpec((1, bs, hkv), _kv_map),
                     pl.BlockSpec((1, bs, hkv), _kv_map)]
        operands += [kscale, vscale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, nb_row),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, kk * num_heads, dh),
                               lambda r, j, pos, tbl: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kk * num_heads, _LANES), jnp.float32),
            pltpu.VMEM((kk * num_heads, _LANES), jnp.float32),
            pltpu.VMEM((kk * num_heads, dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, kk * num_heads, dh), q.dtype),
        cost_estimate=kernel_cost(
            s, nb_row * bs, d, dkv, q.dtype.itemsize, tq=kk,
            kv_itemsize=k.dtype.itemsize,
            scale_hkv=hkv if quant else 0),
        interpret=interpret,
    )(jnp.asarray(qpos, jnp.int32),
      jnp.asarray(tables, jnp.int32), *operands)
    return out.reshape(s, kk, d)


# ------------------------------------------------------------ dispatch

def _chunk_ok(kk, num_heads, interpret):
    """Chunk-lane tiling: the lane-stacked scratch/q blocks are
    [K*H, .]-shaped — any K in interpret mode; the compiled backend
    wants an 8-divisible sublane dim."""
    if kk < 1:
        return False
    return interpret or (kk * num_heads) % 8 == 0


def covers(num_heads, d, dkv, blk_len, paged=False, chunk=1, quant=False,
           shards=1):
    """THE dispatch predicate (flag + shape support), shared by
    ``maybe_slab``/``maybe_paged`` and by ``DecodeEngine.warmup``'s
    resolved-path log — one definition, so the engine can never report
    a path its compiled step didn't take.  ``blk_len``: the slab length
    (slab) or the pool block size (paged).  ``chunk``: query lanes per
    row (1 = plain decode; >1 = the chunked-prefill step).  ``quant``:
    int8 K/V (tighter sublane tiling on the compiled backend).

    ``shards``: a tensor-parallel mesh (docs/serving.md "Sharded
    decode") hands each chip the PER-CHIP stripe — ``num_heads/n``
    query heads, ``d/n``-wide q, ``dkv/n``-wide K/V — and coverage must
    be judged on THAT: a kernel that covers 8 KV heads may not cover
    the 4-head shard (lane-tiling of the narrower Dkv, the smaller
    ``chunk*H`` sublane dim).  The maybe_* call sites inside the
    shard_map see the local widths naturally; this localizes the
    warm-up prediction to match, rejecting to the reference path
    whenever any local width stops tiling."""
    if not decode_kernels_enabled():
        return False
    shards = max(1, int(shards))
    if shards > 1:
        if num_heads % shards or d % shards or dkv % shards:
            return False        # uneven stripes never reach the kernels
        num_heads //= shards
        d //= shards
        dkv //= shards
    interpret = _interpret(None)
    split = _head_split(d, dkv, num_heads)
    if split is None or not _chunk_ok(chunk, num_heads, interpret):
        return False
    if paged:
        return _mosaic_ok(blk_len, dkv, split[0], interpret, quant=quant)
    blk = _pick_block_k(blk_len, _block_k_cap(), interpret,
                        quant=quant)
    return blk is not None and _mosaic_ok(blk, dkv, split[0], interpret,
                                          quant=quant)


def maybe_slab(q, k, v, positions, num_heads, kscale=None, vscale=None):
    """Kernel output [S, D] when the fused slab kernel is enabled and
    covers these shapes; None -> caller takes the reference XLA path."""
    if not covers(num_heads, q.shape[1], k.shape[2], k.shape[1],
                  paged=False, quant=kscale is not None):
        return None
    return decode_attention_slab(q, k, v, positions, num_heads,
                                 interpret=_interpret(None),
                                 kscale=kscale, vscale=vscale)


def maybe_paged(q, k, v, positions, tables, num_heads, kscale=None,
                vscale=None):
    """Kernel output [S, D] when the fused paged kernel is enabled and
    covers these shapes; None -> caller takes the chain-gather path."""
    if not covers(num_heads, q.shape[1], k.shape[2], k.shape[1],
                  paged=True, quant=kscale is not None):
        return None
    return decode_attention_paged(q, k, v, positions, tables, num_heads,
                                  interpret=_interpret(None),
                                  kscale=kscale, vscale=vscale)


def maybe_slab_chunk(q, k, v, qpos, num_heads, kscale=None, vscale=None):
    """Kernel output [S, K, D] when the fused Tq=chunk slab kernel is
    enabled and covers these shapes; None -> the reference XLA path."""
    if not covers(num_heads, q.shape[2], k.shape[2], k.shape[1],
                  paged=False, chunk=q.shape[1],
                  quant=kscale is not None):
        return None
    return decode_attention_slab_chunk(q, k, v, qpos, num_heads,
                                       interpret=_interpret(None),
                                       kscale=kscale, vscale=vscale)


def maybe_paged_chunk(q, k, v, qpos, tables, num_heads, kscale=None,
                      vscale=None):
    """Kernel output [S, K, D] when the fused Tq=chunk paged kernel is
    enabled and covers these shapes; None -> the chain-gather path."""
    if not covers(num_heads, q.shape[2], k.shape[2], k.shape[1],
                  paged=True, chunk=q.shape[1],
                  quant=kscale is not None):
        return None
    return decode_attention_paged_chunk(q, k, v, qpos, tables, num_heads,
                                        interpret=_interpret(None),
                                        kscale=kscale, vscale=vscale)
