"""Flash attention for TPU (Pallas): forward + backward kernels, custom_vjp.

Replaces the materialized [Tq, Tk] softmax of ops.attention.dot_product_
attention for long sequences: logits are computed block-by-block in VMEM
with a running (max, sum) softmax, so HBM traffic is O(T*D) not O(T^2)
(the reference's CUDA layer has no equivalent — pre-transformer era; this
is the TPU-native hot-op treatment its hl_lstm fused kernels got).

Streaming layout: grid (B*H, Tq/BLK_Q, Tk/BLK_K) with the kv dimension
innermost — TPU grids run sequentially per core, so Pallas pipelines the
per-block HBM->VMEM copies while VMEM scratch (acc, running max/sum)
persists across the kv iterations of one q block; only one (q, k, v)
block triple is resident at a time, so VMEM use is O(BLK^2) independent
of sequence length.  Causal blocks entirely above the diagonal are
skipped with @pl.when.  f32 accumulation throughout.

Backward = FlashAttention-2: delta = rowsum(do * o) precomputed (XLA);
one kernel streams q blocks per kv block for dk/dv, one streams kv blocks
per q block for dq, both recomputing p from (q, k, lse).
"""

import contextlib
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30

# test/bench override for the pallas_prefill flag: None = read FLAGS
# (utils/flags.py), else "auto" | "always" | "off" — the
# decode_attention.MODE pattern.  Gates the serving PREFILL routing
# (models/transformer.lm_prefill's batched causal pass) through this
# kernel so no serving path materializes the [Tp, Tp] score matrix;
# "auto" follows use_pallas() (TPU only — the CPU tier-1 default stays
# the masked XLA reference path, preserving greedy bit-identity),
# "always" forces it anywhere (interpret mode off-TPU — the test/smoke
# mode).  Read at TRACE time.
PREFILL_MODE = None


def _prefill_mode():
    if PREFILL_MODE is not None:
        return PREFILL_MODE
    from paddle_tpu.utils.flags import FLAGS
    return getattr(FLAGS, "pallas_prefill", "auto")


@contextlib.contextmanager
def forced_prefill_mode(mode):
    """Temporarily force the prefill-flash routing ("always" | "off" |
    "auto") — tests, the analytic gate, and the A/B bench.  Trace-time:
    wrap the jit/lower call, not just the execution."""
    global PREFILL_MODE
    old = PREFILL_MODE
    PREFILL_MODE = mode
    try:
        yield
    finally:
        PREFILL_MODE = old


def prefill_flash_enabled():
    """True when ``lm_prefill``'s batched causal pass should route
    through ``flash_attention`` (read at trace time by
    ``models/transformer``).  Shape coverage stays flash_attention's
    own: uncoverable blockings fall back to the masked path inside."""
    m = str(_prefill_mode()).lower()
    if m in ("0", "off", "false", "no"):
        return False
    if m in ("1", "on", "always", "true", "yes"):
        return True
    if m != "auto":
        raise ValueError(f"pallas_prefill={m!r} (takes auto | always | "
                         "off)")
    from paddle_tpu.ops import pallas as pk
    return pk.use_pallas()

# Per-row statistics (running max/sum, lse, delta) live lane-REPLICATED in
# [rows, 128] tiles — the same layout
# jax.experimental.pallas.ops.tpu.flash_attention uses; see pallas/common.py.
from paddle_tpu.ops.pallas.common import LANES as _LANES, lanes as _lanes


# ------------------------------------------------------------------ forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, blk_q, blk_k, scale, causal):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    d = q_ref.shape[-1]

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: the block intersects the lower triangle iff
    # qi*blk_q + blk_q - 1 >= ki*blk_k
    needed = (qi * blk_q + blk_q - 1 >= ki * blk_k) if causal else True

    @pl.when(needed)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [blk_q, blk_k]
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0) + qi * blk_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1) + ki * blk_k
            s = jnp.where(rows >= cols, s, _NEG)
        m_prev, l_prev = m_scr[:], l_scr[:]          # [blk_q, _LANES]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - _lanes(m_new, blk_k))
        alpha = jnp.exp(m_prev - m_new)              # [blk_q, _LANES]
        m_scr[:] = m_new
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * _lanes(alpha, d) + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / _lanes(l, d)).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)           # lane-replicated


def _fwd(q, k, v, scale, causal, blk_q, blk_k, interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]

    kernel = functools.partial(_fwd_kernel, blk_q=blk_q, blk_k=blk_k,
                               scale=scale, causal=causal)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, tq // blk_q, tk // blk_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, _LANES), jnp.float32),
            pltpu.VMEM((blk_q, _LANES), jnp.float32),
            pltpu.VMEM((blk_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse[:, :, 0]


# ----------------------------------------------------------------- backward

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, blk_q, blk_k, scale,
                    causal):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    needed = (qi * blk_q + blk_q - 1 >= ki * blk_k) if causal else True

    @pl.when(needed)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                              # [blk_q, _LANES]
        delta = delta_ref[0]                          # [blk_q, _LANES]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [blk_q, blk_k]
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0) + qi * blk_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1) + ki * blk_k
            s = jnp.where(rows >= cols, s, _NEG)
        p = jnp.exp(s - _lanes(lse, blk_k))
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [blk_k, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [blk_q, blk_k]
        ds = p * (dp - _lanes(delta, blk_k)) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [blk_k, d]

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, blk_q, blk_k, scale, causal):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    needed = (qi * blk_q + blk_q - 1 >= ki * blk_k) if causal else True

    @pl.when(needed)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                              # [blk_q, _LANES]
        delta = delta_ref[0]                          # [blk_q, _LANES]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0) + qi * blk_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1) + ki * blk_k
            s = jnp.where(rows >= cols, s, _NEG)
        p = jnp.exp(s - _lanes(lse, blk_k))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - _lanes(delta, blk_k)) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd(scale, causal, blk_q, blk_k, interpret, res, g):
    q, k, v, o, lse = res
    bh, tq, d = q.shape
    tk = k.shape[1]
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # lane-replicated [bh, t, _LANES] views for the kernels (see _LANES note)
    lse_r = jnp.broadcast_to(lse[:, :, None], (bh, tq, _LANES))
    delta_r = jnp.broadcast_to(delta[:, :, None], (bh, tq, _LANES))

    dkv_kernel = functools.partial(_bwd_dkv_kernel, blk_q=blk_q,
                                   blk_k=blk_k, scale=scale, causal=causal)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, tk // blk_k, tq // blk_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, d), jnp.float32),
            pltpu.VMEM((blk_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_r, delta_r)

    dq_kernel = functools.partial(_bwd_dq_kernel, blk_q=blk_q, blk_k=blk_k,
                                  scale=scale, causal=causal)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, tq // blk_q, tk // blk_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse_r, delta_r)
    return dq, dk, dv


# -------------------------------------------------------------- public API

def _tileable(n):
    # _lanes() can slice (n < _LANES) or tile (n % _LANES == 0)
    return n <= _LANES or n % _LANES == 0


def _pick_block(want, n, sublane=8):
    """Largest b <= want that divides n, is sublane-divisible and
    lane-tileable; halve from `want` so a 128-multiple sequence that is
    not a 512-multiple (e.g. T=640) still gets the flash path with
    smaller blocks instead of the materialized-O(T^2) fallback.
    ``sublane``: 8 for f32 operands, 32 for int8 K/V (the s8 VMEM tile
    is (32, 128)) — the decode-side `_pick_block_k` convention."""
    b = min(want, n)
    while b >= sublane:
        if n % b == 0 and b % sublane == 0 and _tileable(b):
            return b
        b //= 2
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhtd(q, k, v, scale, causal, blk_q, blk_k, interpret):
    o, _ = _fwd(q, k, v, scale, causal, blk_q, blk_k, interpret)
    return o


def _flash_fwd_rule(q, k, v, scale, causal, blk_q, blk_k, interpret):
    o, lse = _fwd(q, k, v, scale, causal, blk_q, blk_k, interpret)
    return o, (q, k, v, o, lse)


_flash_bhtd.defvjp(_flash_fwd_rule, _bwd)


def flash_attention(q, k, v, scale=None, causal=False, block_q=512,
                    block_k=512, interpret=None):
    """q: [B, H, Tq, D], k/v: [B, H, Tk, D] -> [B, H, Tq, D].

    Fast path requires Tq/Tk to be multiples of the block size (the model
    zoo pads/buckets sequences to 128-multiples for exactly this reason);
    other shapes fall back to the masked XLA implementation.

    Default 512x512 blocks: measured on a v5e chip at T=8192 causal they
    run 5x faster than 128x128 (grid-overhead-bound) and 2.1x faster than
    XLA's materialized attention — see docs/perf.md.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    blk_q = _pick_block(block_q, tq)
    blk_k = _pick_block(block_k, tk)

    # causal block indexing assumes aligned sequence starts (tq == tk);
    # head width must be lane-tileable for the replicated-stat layout
    if (causal and tq != tk) or blk_q is None or blk_k is None \
            or not _tileable(d):
        from paddle_tpu.ops import attention as attn
        return attn.dot_product_attention(q, k, v, scale=scale,
                                          causal=causal, use_flash=False)

    qf = q.reshape(b * h, tq, d)
    kf = k.reshape(b * h, tk, d)
    vf = v.reshape(b * h, tk, d)
    o = _flash_bhtd(qf, kf, vf, scale, causal, blk_q, blk_k, interpret)
    return o.reshape(b, h, tq, d)


# ----------------------------------------- int8 K/V forward (quant prefill)
#
# The decode kernels' quant contract (ops/pallas/decode_attention.py),
# applied to the batched prefill pass: int8 K/V blocks plus their
# per-(position, KV-head) f32 scale sidecars ride the SAME block-indexed
# DMA stream as the values, and widening happens in REGISTERS —
# `k_i8.astype(f32) * scale` right before the qk dot, elementwise
# identical to quant/kv.dequantize_heads — so no f32 [Tp, Dkv] K/V
# buffer ever exists in HBM (perf/analytic.assert_prefill_kv_quantized
# pins its absence structurally).  Forward-only: prefill is inference;
# the training path keeps the f32 custom_vjp kernel above.
#
# GQA is handled by the index maps, not by widening: the grid carries the
# QUERY head h, and the K/V/scale BlockSpecs select kv-head h//group's
# dh-column stripe (block-unit indexing on the flat [B, Tk, Dkv] cache
# buffer), so repeat_kv_heads never materializes.

# test/bench override for the pallas_prefill_quant flag: None = read
# FLAGS, else "auto" | "always" | "off" — same trace-time contract as
# PREFILL_MODE above.
PREFILL_QUANT_MODE = None


def _prefill_quant_mode():
    if PREFILL_QUANT_MODE is not None:
        return PREFILL_QUANT_MODE
    from paddle_tpu.utils.flags import FLAGS
    return getattr(FLAGS, "pallas_prefill_quant", "auto")


@contextlib.contextmanager
def forced_prefill_quant_mode(mode):
    """Temporarily force the int8-prefill kernel routing ("always" |
    "off" | "auto") — tests, the analytic gate, and the A/B bench.
    Trace-time: wrap the jit/lower call, not just the execution."""
    global PREFILL_QUANT_MODE
    old = PREFILL_QUANT_MODE
    PREFILL_QUANT_MODE = mode
    try:
        yield
    finally:
        PREFILL_QUANT_MODE = old


def prefill_quant_enabled():
    """True when ``lm_prefill(kv_dtype="int8")``'s batched causal pass
    should stream the int8 cache bytes through ``flash_attention_quant``
    instead of dequantizing to a widened f32 K/V first (read at trace
    time by ``models/transformer``).  "auto" follows use_pallas() — the
    CPU tier-1 default stays the dequant + masked XLA reference path,
    preserving the batched-vs-sequential bit-exactness discipline."""
    m = str(_prefill_quant_mode()).lower()
    if m in ("0", "off", "false", "no"):
        return False
    if m in ("1", "on", "always", "true", "yes"):
        return True
    if m != "auto":
        raise ValueError(f"pallas_prefill_quant={m!r} (takes auto | "
                         "always | off)")
    from paddle_tpu.ops import pallas as pk
    return pk.use_pallas()


def _fwd_quant_kernel(q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                      m_scr, l_scr, acc_scr, *, blk_q, blk_k, scale,
                      causal):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    d = q_ref.shape[-1]

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    needed = (qi * blk_q + blk_q - 1 >= ki * blk_k) if causal else True

    @pl.when(needed)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)
        # widen in registers: int8 block * per-(position, head) scale
        # column — the exact dequantize_heads product, so the kernel is
        # bit-identical to flash over the dequantized widened twin
        k = k_ref[0].astype(jnp.float32) * ks_ref[0]   # [blk_k, dh]
        v = v_ref[0].astype(jnp.float32) * vs_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [blk_q, blk_k]
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0) + qi * blk_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1) + ki * blk_k
            s = jnp.where(rows >= cols, s, _NEG)
        m_prev, l_prev = m_scr[:], l_scr[:]          # [blk_q, _LANES]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - _lanes(m_new, blk_k))
        alpha = jnp.exp(m_prev - m_new)              # [blk_q, _LANES]
        m_scr[:] = m_new
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * _lanes(alpha, d) + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / _lanes(l, d)).astype(o_ref.dtype)


def flash_attention_quant(q, k, v, kscale, vscale, num_heads, scale=None,
                          causal=True, block_q=512, block_k=512,
                          interpret=None):
    """Int8-K/V flash prefill: q [B, Tq, D] f32 (flat projection), k/v
    [B, Tk, Dkv] int8 (the cache layout), kscale/vscale [B, Tk, Hkv]
    f32 per-(position, KV-head) sidecars -> [B, H, Tq, dh].

    The sidecars ride the same block-indexed stream as the int8 values
    (each k block pairs with its [blk_k, 1] scale column); widening is
    in-register.  Per-head the K/V stripe is re-streamed (grid is
    (B, H, Tq/blk, Tk/blk)) — the honest CostEstimate below — still
    ~4x fewer KV bytes than a widened f32 stream at dh=128.

    Shape contract (the caller pre-checks via ``prefill_quant_covers``):
    blocks divide Tq/Tk, dh lane-tileable, compiled mode wants
    32-sublane int8 k-tiles; interpret mode takes any divisor."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, tq, d = q.shape
    tk, dkv = k.shape[1], k.shape[2]
    from paddle_tpu.ops.pallas import decode_attention as _dk
    hs = _dk._head_split(d, dkv, num_heads)
    if hs is None:
        raise ValueError(
            f"flash_attention_quant: d={d}, dkv={dkv} do not describe a "
            f"grouped-head layout for num_heads={num_heads}")
    dh, hkv, group = hs
    if not _dk._check_scales("flash_attention_quant", kscale, vscale,
                             (b, tk), hkv):
        raise ValueError("flash_attention_quant: scale sidecars required")
    if k.dtype != jnp.int8 or v.dtype != jnp.int8:
        raise ValueError(
            f"flash_attention_quant: k/v must be int8, got "
            f"{k.dtype}/{v.dtype}")
    scale = scale if scale is not None else 1.0 / math.sqrt(dh)
    blk_q = _pick_block(block_q, tq)
    blk_k = _pick_block(block_k, tk, sublane=8 if interpret else 32)
    if blk_q is None or blk_k is None or not _tileable(dh) \
            or (causal and tq != tk):
        raise ValueError(
            f"flash_attention_quant: uncoverable shape tq={tq} tk={tk} "
            f"dh={dh} (use prefill_quant_covers before calling)")

    qh = q.reshape(b, tq, num_heads, dh).transpose(0, 2, 1, 3)
    kernel = functools.partial(_fwd_quant_kernel, blk_q=blk_q,
                               blk_k=blk_k, scale=scale, causal=causal)
    kv_map = lambda bb, hh, i, j: (bb, j, hh // group)
    o = pl.pallas_call(
        kernel,
        grid=(b, num_heads, tq // blk_q, tk // blk_k),
        in_specs=[
            pl.BlockSpec((1, 1, blk_q, dh),
                         lambda bb, hh, i, j: (bb, hh, i, 0)),
            pl.BlockSpec((1, blk_k, dh), kv_map),
            pl.BlockSpec((1, blk_k, dh), kv_map),
            pl.BlockSpec((1, blk_k, 1), kv_map),
            pl.BlockSpec((1, blk_k, 1), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, blk_q, dh),
                               lambda bb, hh, i, j: (bb, hh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, num_heads, tq, dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, _LANES), jnp.float32),
            pltpu.VMEM((blk_q, _LANES), jnp.float32),
            pltpu.VMEM((blk_q, dh), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            # 2 matmuls (qk, pv), per-head KV re-stream of int8 bytes +
            # f32 scale column, q in + o out
            flops=2 * 2 * b * num_heads * tq * tk * dh,
            bytes_accessed=(b * num_heads * 2 * tk * (dh * 1 + 4)
                            + 2 * b * num_heads * tq * dh * 4),
            transcendentals=b * num_heads * tq * tk),
        interpret=interpret,
    )(qh, k, v, kscale, vscale)
    return o


def prefill_quant_covers(b, tq, tk, d, dkv, num_heads, interpret,
                         block_q=512, block_k=512):
    """True when flash_attention_quant's blocking covers the shape —
    the dispatch predicate (decode_attention.covers's twin)."""
    from paddle_tpu.ops.pallas import decode_attention as _dk
    hs = _dk._head_split(d, dkv, num_heads)
    if hs is None:
        return False
    dh, _, _ = hs
    if not _tileable(dh) or tq != tk:
        return False
    return (_pick_block(block_q, tq) is not None
            and _pick_block(block_k, tk,
                            sublane=8 if interpret else 32) is not None)


def maybe_prefill_quant(q, k_set, v_set, sk, sv, num_heads):
    """lm_prefill's int8 dispatch: q [B, Tp, D] f32, k_set/v_set
    [B, Tp, Dkv] int8 (the just-quantized cache writes), sk/sv
    [B, Tp, Hkv] scales -> attention output [B, Tp, D], or None when
    the routing is off / the shape is uncoverable (caller falls back to
    the dequant + masked XLA reference path)."""
    if sk is None or not prefill_quant_enabled():
        return None
    interpret = jax.default_backend() != "tpu"
    b, tp, d = q.shape
    dkv = k_set.shape[-1]
    if not prefill_quant_covers(b, tp, tp, d, dkv, num_heads, interpret):
        return None
    o = flash_attention_quant(q, k_set, v_set, sk, sv, num_heads,
                              causal=True, interpret=interpret)
    return o.transpose(0, 2, 1, 3).reshape(b, tp, d)
