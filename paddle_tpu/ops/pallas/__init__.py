"""Pallas TPU kernels for the hot ops.

The reference keeps its hot paths in hand-written CUDA
(cuda/src/hl_cuda_lstm.cu fused LSTM, hl_top_k.cu, hl_cuda_matrix.cu); the
TPU-native equivalents are Pallas kernels where XLA's own fusion isn't
already optimal:

  flash_attention — blocked softmax(QK^T)V with O(T) memory (fwd + bwd
                    kernels, custom_vjp), the MXU/HBM-friendly formulation
                    of attention for the transformer/NMT model families.

  decode_attention — fused slab/paged decode attention for the serving
                    hot path (one KV read per step, block table walked
                    via scalar prefetch; gated by the trace-time
                    `pallas_decode` flag — see that module's docstring
                    and docs/perf.md "Fused decode kernels").

Kernels run on TPU; on CPU they fall back to interpret mode (tests) or the
XLA reference implementation (callers check `use_pallas()`).
"""

import jax

from paddle_tpu.ops.pallas.flash_attention import flash_attention


def use_pallas():
    """True when the default backend compiles Pallas natively (TPU)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


__all__ = ["flash_attention", "use_pallas"]
