"""Elementwise / small-algebra layer ops.

Reference zoo (SURVEY.md §2.2 "Dense/basic layers"): AddtoLayer,
InterpolationLayer, PowerLayer, ScalingLayer, SlopeInterceptLayer,
ConvexCombinationLayer, SumToOneNormLayer, CosSimLayer, CosSimVecMatLayer,
OuterProdLayer, TransLayer, RotateLayer, MultiplexLayer, ConvShiftLayer,
TensorLayer, BilinearInterpLayer(-> conv.py), FeatureMapExpandLayer,
ResizeLayer, DataNormLayer, ParameterReluLayer.
"""

import jax.numpy as jnp

from paddle_tpu.ops import activations


def addto(*xs, bias=None, act=None):
    y = xs[0]
    for x in xs[1:]:
        y = y + x
    if bias is not None:
        y = y + bias
    return activations.get(act)(y)


def interpolation(w, a, b):
    """w in [0,1] per-row: w*a + (1-w)*b.  w: [..., 1] or [...]."""
    if w.ndim == a.ndim - 1:
        w = w[..., None]
    return w * a + (1.0 - w) * b


def power(p, x):
    """Per-row exponent: x ** p (reference PowerLayer)."""
    if p.ndim == x.ndim - 1:
        p = p[..., None]
    return x ** p


def scaling(s, x):
    """Per-row scalar scale (reference ScalingLayer)."""
    if s.ndim == x.ndim - 1:
        s = s[..., None]
    return s * x


def slope_intercept(x, slope=1.0, intercept=0.0):
    return slope * x + intercept


def sum_to_one_norm(x, eps=1e-12):
    return x / (jnp.sum(x, axis=-1, keepdims=True) + eps)


def cos_sim(a, b, scale=1.0, eps=1e-8):
    """Row-wise cosine similarity -> [..., 1] (reference CosSimLayer, scale=5)."""
    dot = jnp.sum(a * b, axis=-1, keepdims=True)
    na = jnp.sqrt(jnp.sum(a * a, axis=-1, keepdims=True))
    nb = jnp.sqrt(jnp.sum(b * b, axis=-1, keepdims=True))
    return scale * dot / jnp.maximum(na * nb, eps)


def cos_sim_vec_mat(vec, mat, scale=1.0, eps=1e-8):
    """vec [B, D], mat [B, K, D] -> [B, K] cos sims (CosSimVecMatLayer)."""
    dot = jnp.einsum("bd,bkd->bk", vec, mat)
    nv = jnp.linalg.norm(vec, axis=-1, keepdims=True)
    nm = jnp.linalg.norm(mat, axis=-1)
    return scale * dot / jnp.maximum(nv * nm, eps)


def outer_prod(a, b):
    """[B, M], [B, N] -> [B, M*N] (reference OuterProdLayer)."""
    out = jnp.einsum("bm,bn->bmn", a, b)
    return out.reshape(out.shape[0], -1)


def trans(x):
    """Matrix transpose of a [H, W]-shaped row batch is meaningless without
    frame info; reference TransLayer transposes the whole batch matrix."""
    return x.T


def rotate(x, height, width):
    """Rotate each row's [C, H, W] feature map 90° CCW (reference RotateLayer)."""
    b = x.shape[0]
    c = x.shape[-1] // (height * width)
    img = x.reshape(b, c, height, width)
    rot = jnp.rot90(img, k=1, axes=(2, 3))
    return rot.reshape(b, -1)


def multiplex(index, *xs):
    """Per-row select among K same-shaped inputs (reference MultiplexLayer).
    index: int [B]; xs: K arrays [B, D]."""
    stacked = jnp.stack(xs, axis=1)          # [B, K, D]
    idx = index.reshape(index.shape[0])      # accept [B] or [B, 1] columns
    idx = jnp.clip(idx.astype(jnp.int32), 0, len(xs) - 1)
    return jnp.take_along_axis(stacked, idx[:, None, None], axis=1)[:, 0]


def conv_shift(a, b):
    """Circular convolution (reference ConvShiftLayer, NTM-style shift).
    a: [B, M], b: [B, N] (N odd, N<M) -> [B, M]."""
    m = a.shape[-1]
    n = b.shape[-1]
    half = n // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(-half, half + 1)[None, :]) % m
    gathered = a[:, idx]                      # [B, M, N]
    return jnp.einsum("bmn,bn->bm", gathered, b)


def tensor_product(a, b, w, act=None):
    """Reference TensorLayer: y_k = a @ W_k @ b^T.
    a: [B, M], b: [B, N], w: [K, M, N] -> [B, K]."""
    y = jnp.einsum("bm,kmn,bn->bk", a, w, b)
    return activations.get(act)(y)


def feature_map_expand(x, num_filters, as_row_vector=True):
    """[B, D] -> [B, num_filters*D] by tiling (reference FeatureMapExpandLayer).

    as_row_vector=True: output = [x; x; ...] (num_filters copies of the whole
    row). False: each element repeated num_filters times in place
    ([x0 x num_filters, x1 x num_filters, ...])."""
    if as_row_vector:
        tiled = jnp.tile(x[:, None, :], (1, num_filters, 1))
    else:
        tiled = jnp.tile(x[:, :, None], (1, 1, num_filters))
    return tiled.reshape(x.shape[0], -1)


def resize(x, size):
    """Reinterpret batch rows with a new row width (reference ResizeLayer)."""
    return x.reshape(-1, size)


def prelu(x, alpha):
    """ParameterReluLayer: per-partition learned negative slope."""
    return jnp.where(x >= 0, x, alpha * x)


def data_norm(x, mean, std_inv, strategy="z-score", min_=None, span_inv=None):
    """DataNormLayer: z-score / min-max normalization with precomputed stats."""
    if strategy == "min-max":
        return (x - min_) * span_inv
    return (x - mean) * std_inv


def pad_value_replace(x, mask, value=0.0):
    return jnp.where(mask > 0, x, value)
