"""Speculative decoding: the draft trunk and its jitted k-token rollout.

Leviathan-style greedy draft/verify on the slot engine
(docs/serving.md "Speculative decoding").  A small trunk — fewer layers
(and optionally fewer heads / int8 weights) than the target, sharing the
target's embedding and vocab — runs its OWN k-token autoregressive
rollout per slot against a private slab KV cache, and the TARGET's one
chunked step (``lm_decode_chunk_slots``/``_paged`` with
``all_lanes=True``) then scores every drafted lane at once.  The draft
only ever changes SPEED: acceptance keeps exactly the longest prefix the
target itself would have emitted greedily, so streams stay token-
identical to ``lm_generate`` no matter how good or bad the draft is.

Trace discipline matches the target engine: ONE jitted rollout function
(chunk-ingest the committed tokens, then k-1 static-unrolled single-
position steps), warmed exactly once; k is a constructor constant and
per-slot feed lengths/positions are data, so acceptance churn never
retraces.  The draft cache is epoch-guarded like the target's
(``reset()`` bumps the epoch; an in-flight rollout's cache commit is
dropped if it lost the race) — PR 6 supervisor recovery resets BOTH
caches and the re-seat replay rebuilds them through the same feed path.

Bookkeeping contract with ``DecodeEngine`` (the ``_d_feed``/``_d_pos``
invariant): rollout K/V writes past the committed stream are NEVER
counted as ingested.  The engine re-feeds every committed token through
``rollout`` (matched drafts re-feed identical values; mismatches feed
the corrected token), and because the chunk step writes all lanes
BEFORE attending, stale rollout writes at those positions are
overwritten before anything reads them — the slab needs no rollback at
all.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import transformer
from paddle_tpu.testing.trace import expect_traces
from paddle_tpu.utils.error import ConfigError


def make_draft(params, layers=2, quantize=False):
    """Derive a draft parameter tree from the target's: same embedding /
    positional table / final LN / vocab (ARRAYS SHARED, not copied — the
    draft adds only ``layers`` blocks of weight bytes), trunk truncated
    to the first ``layers`` enc blocks.  The shallow trunk stays a
    well-formed LM the transformer entry points accept unchanged; with
    ``quantize=True`` the blocks are int8-quantized via PR 14's
    ``quant/weights.py`` (the shared embedding is quantized too — the
    target holds its own float copy, so this only narrows the draft's
    weight stream)."""
    n = len(params["enc"])
    if not 1 <= layers <= n:
        raise ConfigError(
            f"draft layers must be in [1, {n}] (the target's enc depth), "
            f"got {layers}")
    draft = dict(params)
    draft["enc"] = list(params["enc"][:layers])
    if quantize:
        from paddle_tpu.quant import weights as qw
        draft = qw.quantize_lm(draft)
    return draft


class DraftTrunk:
    """The draft model half of speculative decoding: slab KV cache with
    the target engine's slot indexing, one jitted rollout producing k
    greedy draft tokens per slot per call.

    ``rollout(tokens, positions, lengths)``: chunk-ingest each row's
    ``lengths[r]`` committed tokens starting at ``positions[r]`` (lanes
    past the length are ignored), then unroll ``k - 1`` single-position
    steps feeding the draft's own argmax back in.  Returns drafts
    [num_slots, k] (row r's candidates for stream positions
    ``positions[r] + lengths[r] ..``) — or None if ``reset()`` won the
    epoch race mid-call (the caller arms nothing and retries next step).
    """

    def __init__(self, params, *, k, num_slots, max_len, chunk,
                 num_heads=8, moe_top_k=2, pos_type="learned",
                 name="draft", warm=False, mesh=None):
        if k < 1:
            raise ConfigError(f"speculate_k must be >= 1, got {k}")
        if chunk < 1:
            raise ConfigError(f"draft chunk must be >= 1, got {chunk}")
        # tensor-parallel rollout (docs/serving.md "Sharded decode"): the
        # draft shards EXACTLY like its target — same head/vocab stripe
        # policy, its own private shard_map — so a sharded engine's
        # speculation path never leaves the mesh.  The draft shares the
        # target's head count and vocab, so the engine's divisibility
        # validation covers it; standalone construction re-checks.
        self.mesh = mesh
        self.mesh_shards = 1
        self._shard_axis = None
        if mesh is not None:
            from paddle_tpu.parallel import sharding as _psh
            from paddle_tpu.parallel.mesh import AXIS_MODEL
            from jax.sharding import NamedSharding
            self._psh = _psh
            self._shard_axis = AXIS_MODEL
            self.mesh_shards = int(mesh.shape[AXIS_MODEL])
            probs = _psh.lm_shard_problems(params, num_heads,
                                           self.mesh_shards)
            if probs:
                raise ConfigError(
                    f"{name}: cannot shard the draft trunk "
                    f"{self.mesh_shards} ways: " + "; ".join(probs))
            pspecs = _psh.lm_decode_param_specs(params, AXIS_MODEL)
            params = jax.tree_util.tree_map(
                lambda l, s: jax.device_put(l, NamedSharding(mesh, s)),
                params, pspecs)
        self.params = params
        self.k = int(k)
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.chunk = int(chunk)
        self.num_heads = num_heads
        self.moe_top_k = moe_top_k
        self.pos_type = pos_type
        self.name = name
        self._trace = [0]
        self._warm = False
        self._epoch = 0
        self._epoch_lock = threading.Lock()
        self._cache = self._place_cache(
            transformer.init_lm_cache(params, self.num_slots,
                                      self.max_len))

        axis = self._shard_axis
        heads = (self.num_heads // self.mesh_shards if axis is not None
                 else self.num_heads)

        def _model(p, cache, tokens, positions, lengths):
            logits, cache = transformer.lm_decode_chunk_slots(
                p, tokens, positions, lengths, cache, heads,
                self.moe_top_k, self.pos_type, shard_axis=axis)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            drafts = [nxt]
            # rollout writes land past the committed stream; the clamp
            # keeps the scatter in-bounds for rows parked at the cache
            # edge (their junk write is re-fed before anything attends)
            base = positions + lengths
            for i in range(self.k - 1):
                qp = jnp.minimum(base + i, self.max_len - 1)
                logits, cache = transformer.lm_decode_step_slots(
                    p, nxt, qp, cache, heads, self.moe_top_k,
                    self.pos_type, shard_axis=axis)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                drafts.append(nxt)
            return jnp.stack(drafts, axis=1), cache

        if axis is not None:
            # ONE shard_map around the whole rollout: the k-1 unrolled
            # steps stay inside, so the only collectives are the model's
            # own seams — no per-step re-entry
            from jax.sharding import PartitionSpec as _P
            pspecs = self._psh.lm_decode_param_specs(self.params, axis)
            cspecs = self._psh.lm_cache_specs(self._cache, axis)
            body = self._psh.shard_map(
                _model, mesh=mesh,
                in_specs=(pspecs, cspecs, _P(), _P(), _P()),
                out_specs=(_P(), cspecs), check_vma=False)
        else:
            body = _model

        def _draft_fn(p, cache, tokens, positions, lengths):
            self._trace[0] += 1
            return body(p, cache, tokens, positions, lengths)

        self._jit = jax.jit(_draft_fn, donate_argnums=(1,))
        if warm:
            self.warmup()

    def _place_cache(self, cache):
        """Shard a fresh draft slab over the mesh (trailing head-stripe
        axis, like the target's) — identity when unsharded."""
        if self._shard_axis is None:
            return cache
        from jax.sharding import NamedSharding
        specs = self._psh.lm_cache_specs(cache, self._shard_axis)
        return jax.tree_util.tree_map(
            lambda l, s: jax.device_put(l, NamedSharding(self.mesh, s)),
            cache, specs)

    @property
    def trace_count(self):
        return self._trace[0]

    def _dummy_feed(self):
        tokens = np.zeros((self.num_slots, self.chunk), np.int32)
        positions = np.zeros((self.num_slots,), np.int32)
        lengths = np.ones((self.num_slots,), np.int32)
        return tokens, positions, lengths

    def rollout(self, tokens, positions, lengths):
        with self._epoch_lock:
            epoch = self._epoch
        drafts, cache = self._jit(self.params, self._cache,
                                  jnp.asarray(tokens, jnp.int32),
                                  jnp.asarray(positions, jnp.int32),
                                  jnp.asarray(lengths, jnp.int32))
        with self._epoch_lock:
            if epoch != self._epoch:
                return None          # reset() raced us; drop the commit
            self._cache = cache
        return np.asarray(drafts)

    def reset(self):
        """Invalidate the draft cache (supervisor recovery / engine
        reset): epoch bump drops any in-flight rollout's commit, fresh
        slab rebuilt from the params.  Host-side feed bookkeeping lives
        in the engine and is re-seeded by the re-seat paths."""
        with self._epoch_lock:
            self._epoch += 1
            self._cache = self._place_cache(transformer.init_lm_cache(
                self.params, self.num_slots, self.max_len))

    def warmup(self):
        """Trace the rollout exactly once at the live shapes.
        Idempotent, like the engine's."""
        if self._warm:
            return
        self._warm = True
        tokens, positions, lengths = self._dummy_feed()
        with expect_traces(lambda: self._trace[0], 1,
                           f"{self.name} rollout warmup",
                           hint="draft rollout shapes must be fixed at "
                                "construction (k/chunk/num_slots)"):
            out = self.rollout(tokens, positions, lengths)
        assert out is not None and out.shape == (self.num_slots, self.k)
        self.reset()

    def lower(self):
        """Lowered (unspecialized-to-device-data) rollout for the
        analytic bench's compiled-HLO inspection."""
        tokens, positions, lengths = self._dummy_feed()
        return self._jit.lower(self.params, self._cache,
                               jnp.asarray(tokens), jnp.asarray(positions),
                               jnp.asarray(lengths))
