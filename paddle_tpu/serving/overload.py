"""Adaptive overload control for the router (docs/serving.md §8).

The replica tier already defends itself — bounded queues 429, breakers
503 — but those are cliff-edge defenses: by the time a replica sheds,
every queued request behind it has already eaten the latency.  This
module is the router-side feedback layer that keeps the fleet INSIDE its
SLO while the autoscaler (serving/autoscaler.py) changes the fleet size
underneath it, in three coupled pieces:

* ``AIMDLimiter`` — a TCP-style additive-increase/multiplicative-
  decrease concurrency limit ahead of the dispatch path.  Every clean
  completion nudges the limit up by ``increase/limit`` (one full +1 per
  round of the window); every overload signal from upstream (replica
  429/503, a shed) multiplies it by ``decrease`` at most once per
  ``decrease_cooldown_s`` (one congestion event per RTT, not one per
  queued victim).  The limit converges to what the fleet actually
  sustains instead of a hand-tuned constant that is wrong at every
  fleet size.

* PRIORITY CLASSES with deadline-aware shedding — requests carry a
  class (``"priority"`` in the body or the ``X-Priority`` header):
  ``interactive`` > ``standard`` (default) > ``background``.  Lower
  classes see a smaller slice of the limit (``CLASS_HEADROOM``), so as
  load rises the lowest class is shed FIRST, and a request whose own
  deadline cannot survive the estimated queue wait (in-flight work over
  the observed drain rate) is shed immediately instead of timing out
  inside the fleet.  Every shed is an honest HTTP 429 with a
  Retry-After derived from the observed drain rate — the excess
  in-flight work divided by completions/second, not a constant.

* ``BrownoutLadder`` — graceful degradation under SUSTAINED SLO breach
  (TTFT p99 over ``slo_ttft_ms`` for ``enter_hold_s``), one rung at a
  time, each rung trading a little quality for a lot of capacity:

      rung 1  hedge_off         stop hedging (no duplicate work)
      rung 2  token_cap         cap per-request max_tokens
      rung 3  shed_background   shed ALL background-class traffic

  Recovery walks DOWN one rung per sustained-healthy ``exit_hold_s``,
  and every entry/exit bumps an explicit per-rung counter — the
  degradation is observable and provably reversible, never a silent
  quality cliff.  ``slo_ttft_ms=0`` (the default) disables the ladder;
  the limiter still runs but its default bounds are far above any
  normal load, so the router's default behavior is unchanged.

Everything takes an injectable monotonic ``clock`` and mutates only
under one lock, so control decisions replay bit-for-bit in tests
(tests/test_autoscaler.py) on a simulated clock.
"""

import math
import threading
import time

# priority classes, highest first.  The default for unlabeled traffic is
# "standard" so explicitly-interactive traffic can be protected ABOVE
# the default and bulk traffic demoted below it.
PRIORITY_CLASSES = ("interactive", "standard", "background")
DEFAULT_PRIORITY = "standard"

# fraction of the AIMD limit each class may fill: background saturates
# (and sheds) first, interactive last — the shed order under pressure.
CLASS_HEADROOM = {"interactive": 1.0, "standard": 0.85, "background": 0.6}

# brownout rungs in entry order (rung k = RUNGS[k-1]; rung 0 = healthy)
BROWNOUT_RUNGS = ("hedge_off", "token_cap", "shed_background")


class ShedError(RuntimeError):
    """The overload controller refused this request (HTTP 429).  Carries
    the honest Retry-After (seconds, derived from the observed drain
    rate) and the shedding reason for the metrics/counters."""

    def __init__(self, msg, retry_after_s, reason, priority):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.reason = reason            # "limit" | "deadline" | "brownout"
        self.priority = priority


class DrainRate:
    """Observed request completion rate over a sliding window — the
    denominator of every honest Retry-After.  A bounded ring of
    completion timestamps under the injected clock.

    Deliberately NOT built on utils/stats.Histogram's clock-stamped
    ring: here the timestamps ARE the data (rate() needs the oldest
    in-window completion time for its span), while the Histogram ring
    stores value samples and only uses times for window filtering."""

    def __init__(self, window_s=30.0, max_samples=4096, clock=None):
        self.window_s = float(window_s)
        self.clock = clock or time.monotonic
        self._times = []
        self._max = int(max_samples)
        self._i = 0
        self._lock = threading.Lock()

    def observe(self):
        now = self.clock()
        with self._lock:
            if len(self._times) < self._max:
                self._times.append(now)
            else:
                self._times[self._i % self._max] = now
            self._i += 1

    def rate(self):
        """Completions per second over the window (0.0 when idle).  The
        span is floored at one second: a single batch landing its
        completions within a millisecond must read as "N per second at
        most", not a near-infinite rate that would silently disable
        deadline shedding."""
        now = self.clock()
        with self._lock:
            recent = [t for t in self._times if t >= now - self.window_s]
        if not recent:
            return 0.0
        span = max(now - min(recent), 1.0)
        return len(recent) / span


class AIMDLimiter:
    """Additive-increase / multiplicative-decrease concurrency limiter.

    acquire(priority) admits while the in-flight count is under the
    class's slice of the current limit; release(overloaded=...) returns
    the permit and drives the AIMD feedback.  All state under one lock,
    all time from the injected clock.
    """

    def __init__(self, initial=64, min_limit=4, max_limit=4096,
                 increase=1.0, decrease=0.5, decrease_cooldown_s=1.0,
                 clock=None):
        if not 0.0 < float(decrease) < 1.0:
            raise ValueError("decrease must be in (0, 1)")
        self.limit = float(initial)
        self.min_limit = float(min_limit)
        self.max_limit = float(max_limit)
        self.increase = float(increase)
        self.decrease = float(decrease)
        self.decrease_cooldown_s = float(decrease_cooldown_s)
        self.clock = clock or time.monotonic
        self.inflight = 0
        self.decreases_total = 0
        self._last_decrease = -math.inf
        self._lock = threading.Lock()

    def headroom(self, priority):
        return CLASS_HEADROOM.get(priority, CLASS_HEADROOM[
            DEFAULT_PRIORITY])

    def try_acquire(self, priority=DEFAULT_PRIORITY):
        """Take one permit if the class's slice has room; True/False."""
        with self._lock:
            if self.inflight < self.limit * self.headroom(priority):
                self.inflight += 1
                return True
            return False

    def release(self, overloaded=False, success=True):
        """Return the permit.  A CLEAN COMPLETION (success=True, not
        overloaded) grows the limit by increase/limit (≈ +increase per
        full window of completions); an overload signal halves it, at
        most once per cooldown so one congestion event is charged once,
        not once per victim.  A plain failure (replica 4xx/5xx, timeout,
        broken stream) moves the limit NOWHERE — an error storm is not
        evidence the fleet can take more concurrency."""
        with self._lock:
            self.inflight = max(0, self.inflight - 1)
            if overloaded:
                now = self.clock()
                if now - self._last_decrease >= self.decrease_cooldown_s:
                    self._last_decrease = now
                    self.limit = max(self.min_limit,
                                     self.limit * self.decrease)
                    self.decreases_total += 1
            elif success:
                self.limit = min(self.max_limit,
                                 self.limit + self.increase
                                 / max(self.limit, 1.0))

    def snapshot(self):
        with self._lock:
            return {"limit": round(self.limit, 2),
                    "inflight": self.inflight,
                    "decreases_total": self.decreases_total}


class BrownoutLadder:
    """Rung-by-rung graceful degradation under sustained SLO breach.

    ``observe(ttft_p99_s)`` is called from the router's poll loop with
    the recent-window TTFT p99; the ladder steps UP one rung after the
    breach has held ``enter_hold_s``, steps DOWN one rung after health
    has held ``exit_hold_s``, and never moves more than one rung per
    observation — with per-rung entry/exit counters so every transition
    is visible in /metrics.  ``slo_ttft_s`` <= 0 disables the ladder
    (rung pinned at 0)."""

    def __init__(self, slo_ttft_s=0.0, enter_hold_s=3.0, exit_hold_s=5.0,
                 clock=None):
        self.slo_ttft_s = float(slo_ttft_s)
        self.enter_hold_s = float(enter_hold_s)
        self.exit_hold_s = float(exit_hold_s)
        self.clock = clock or time.monotonic
        self.rung = 0                   # 0 = healthy .. len(RUNGS)
        self.entries = {r: 0 for r in BROWNOUT_RUNGS}
        self.exits = {r: 0 for r in BROWNOUT_RUNGS}
        self._breach_since = None
        self._healthy_since = None
        self._lock = threading.Lock()

    @property
    def enabled(self):
        return self.slo_ttft_s > 0

    def observe(self, ttft_p99_s, now=None):
        """One SLO evaluation; returns the (possibly new) rung."""
        if not self.enabled:
            return 0
        now = self.clock() if now is None else now
        breached = ttft_p99_s > self.slo_ttft_s
        with self._lock:
            if breached:
                self._healthy_since = None
                if self._breach_since is None:
                    self._breach_since = now
                if (now - self._breach_since >= self.enter_hold_s
                        and self.rung < len(BROWNOUT_RUNGS)):
                    rung_name = BROWNOUT_RUNGS[self.rung]
                    self.rung += 1
                    self.entries[rung_name] += 1
                    self._breach_since = now    # next rung needs its own
                    #                             sustained breach
            else:
                self._breach_since = None
                if self._healthy_since is None:
                    self._healthy_since = now
                if (now - self._healthy_since >= self.exit_hold_s
                        and self.rung > 0):
                    self.rung -= 1
                    self.exits[BROWNOUT_RUNGS[self.rung]] += 1
                    self._healthy_since = now   # one rung per hold period
            return self.rung

    # --- the three degradation switches the router consults ---

    def hedging_allowed(self):
        return self.rung < 1

    def capping_tokens(self):
        """True when rung >= 2: the router must cap per-request
        max_tokens (the cap VALUE lives on the OverloadController —
        ``cap_max_tokens`` applies it)."""
        return self.rung >= 2

    def shed_background(self):
        return self.rung >= 3

    def snapshot(self):
        with self._lock:
            return {"rung": self.rung,
                    "entries": dict(self.entries),
                    "exits": dict(self.exits)}


class OverloadController:
    """The facade the router dispatches through: AIMD admission with
    priority classes, deadline-aware shedding, honest Retry-After, and
    the brownout ladder.  One instance per Router."""

    def __init__(self, limiter=None, ladder=None, drain_window_s=30.0,
                 brownout_max_tokens=32, clock=None):
        self.clock = clock or time.monotonic
        self.limiter = limiter or AIMDLimiter(clock=self.clock)
        self.ladder = ladder or BrownoutLadder(clock=self.clock)
        self.drain = DrainRate(window_s=drain_window_s, clock=self.clock)
        self.brownout_max_tokens = int(brownout_max_tokens)
        self._lock = threading.Lock()
        self.shed_total = {p: 0 for p in PRIORITY_CLASSES}
        self.shed_reasons = {"limit": 0, "deadline": 0, "brownout": 0}
        self.admitted_total = {p: 0 for p in PRIORITY_CLASSES}
        self.hedges_suppressed_total = 0
        self.token_caps_applied_total = 0

    # ------------------------------------------------------------ admit

    @staticmethod
    def parse_priority(value):
        """Normalize a request's priority label; unknown/absent labels
        map to the default class (never a 400 — priority is advisory)."""
        if isinstance(value, str) and value.lower() in PRIORITY_CLASSES:
            return value.lower()
        return DEFAULT_PRIORITY

    def retry_after_s(self):
        """Honest backoff hint: the excess in-flight work over the
        observed drain rate — 'come back when the queue you would have
        joined has actually drained', clamped to [1, 30]."""
        rate = self.drain.rate()
        snap = self.limiter.snapshot()
        excess = max(1.0, snap["inflight"] - snap["limit"] + 1.0)
        if rate <= 0:
            return 1
        return max(1, min(30, int(math.ceil(excess / rate))))

    def admit(self, priority=DEFAULT_PRIORITY, deadline_ms=None):
        """Take a dispatch permit or raise ``ShedError`` (HTTP 429).
        Shedding order under pressure: brownout rung 3 sheds all
        background traffic; then the class slices of the AIMD limit
        (background saturates first); then the deadline check sheds a
        request that could not survive the estimated wait anyway."""
        priority = self.parse_priority(priority)
        if priority == "background" and self.ladder.shed_background():
            self._count_shed(priority, "brownout")
            raise ShedError(
                "brownout rung 3: background traffic is shed",
                self.retry_after_s(), "brownout", priority)
        if deadline_ms is not None:
            rate = self.drain.rate()
            if rate > 0:
                # the fleet serves up to `limit` requests in PARALLEL:
                # only the queue beyond the limit is wait this request
                # would actually eat (at healthy concurrency the excess
                # is 0 and no deadline is ever shed)
                snap = self.limiter.snapshot()
                excess = max(0.0, snap["inflight"] - snap["limit"])
                est_wait_s = excess / rate
                if est_wait_s > float(deadline_ms) / 1e3:
                    self._count_shed(priority, "deadline")
                    raise ShedError(
                        f"estimated queue wait {est_wait_s:.1f}s exceeds "
                        f"the request deadline {deadline_ms}ms",
                        self.retry_after_s(), "deadline", priority)
        if not self.limiter.try_acquire(priority):
            self._count_shed(priority, "limit")
            raise ShedError(
                f"concurrency limit reached for class {priority!r} "
                f"(AIMD limit {self.limiter.snapshot()['limit']})",
                self.retry_after_s(), "limit", priority)
        with self._lock:
            self.admitted_total[priority] += 1
        return priority

    def release(self, overloaded=False, completed=True):
        """Return the permit taken by a successful admit().
        overloaded: the upstream signalled congestion (replica 429/503)
        — drives the multiplicative decrease.  completed: the request
        genuinely finished (feeds the drain-rate estimator AND gates the
        additive increase — failures move the limit nowhere)."""
        self.limiter.release(overloaded=overloaded, success=completed)
        if completed:
            self.drain.observe()

    def _count_shed(self, priority, reason):
        with self._lock:
            self.shed_total[priority] += 1
            self.shed_reasons[reason] += 1

    # ------------------------------------------------------- brownout taps

    def observe_slo(self, ttft_p99_s, now=None):
        """Feed one recent-window TTFT p99 reading into the ladder
        (called from the router's poll loop)."""
        return self.ladder.observe(ttft_p99_s, now=now)

    def hedging_allowed(self):
        if self.ladder.hedging_allowed():
            return True
        with self._lock:
            self.hedges_suppressed_total += 1
        return False

    def cap_max_tokens(self, requested):
        """Brownout rung 2: cap a request's effective max_tokens.
        Returns the capped value (and counts the cap when it bit)."""
        if not self.ladder.capping_tokens():
            return requested
        capped = min(int(requested), self.brownout_max_tokens)
        if capped < int(requested):
            with self._lock:
                self.token_caps_applied_total += 1
        return capped

    # ------------------------------------------------------------ render

    def snapshot(self):
        with self._lock:
            out = {
                "shed_total": dict(self.shed_total),
                "shed_reasons": dict(self.shed_reasons),
                "admitted_total": dict(self.admitted_total),
                "hedges_suppressed_total": self.hedges_suppressed_total,
                "token_caps_applied_total": self.token_caps_applied_total,
            }
        out["limiter"] = self.limiter.snapshot()
        out["brownout"] = self.ladder.snapshot()
        out["drain_rate_per_s"] = round(self.drain.rate(), 3)
        return out

    def render_lines(self, name):
        """Prometheus text lines (appended to the router's /metrics)."""
        s = self.snapshot()
        lines = [
            f"# HELP {name}_overload_limit current AIMD concurrency limit",
            f"# TYPE {name}_overload_limit gauge",
            f"{name}_overload_limit {s['limiter']['limit']}",
            f"# HELP {name}_overload_inflight admitted in-flight requests",
            f"# TYPE {name}_overload_inflight gauge",
            f"{name}_overload_inflight {s['limiter']['inflight']}",
            f"# HELP {name}_overload_decreases_total multiplicative "
            "limit decreases (congestion events)",
            f"# TYPE {name}_overload_decreases_total counter",
            f"{name}_overload_decreases_total "
            f"{s['limiter']['decreases_total']}",
            f"# HELP {name}_overload_shed_total requests shed 429 by the "
            "overload controller, by priority class",
            f"# TYPE {name}_overload_shed_total counter",
        ]
        for p in PRIORITY_CLASSES:
            lines.append(f'{name}_overload_shed_total{{priority="{p}"}} '
                         f"{s['shed_total'][p]}")
        lines += [
            f"# HELP {name}_overload_shed_reason_total sheds by cause",
            f"# TYPE {name}_overload_shed_reason_total counter",
        ]
        for r in sorted(s["shed_reasons"]):
            lines.append(f'{name}_overload_shed_reason_total'
                         f'{{reason="{r}"}} {s["shed_reasons"][r]}')
        lines += [
            f"# HELP {name}_overload_admitted_total admitted dispatches, "
            "by priority class",
            f"# TYPE {name}_overload_admitted_total counter",
        ]
        for p in PRIORITY_CLASSES:
            lines.append(
                f'{name}_overload_admitted_total{{priority="{p}"}} '
                f"{s['admitted_total'][p]}")
        lines += [
            f"# HELP {name}_brownout_rung current brownout ladder rung "
            "(0 healthy; 1 hedge_off, 2 token_cap, 3 shed_background)",
            f"# TYPE {name}_brownout_rung gauge",
            f"{name}_brownout_rung {s['brownout']['rung']}",
            f"# HELP {name}_brownout_entries_total rung entries, by rung",
            f"# TYPE {name}_brownout_entries_total counter",
        ]
        for r in BROWNOUT_RUNGS:
            lines.append(f'{name}_brownout_entries_total{{rung="{r}"}} '
                         f"{s['brownout']['entries'][r]}")
        lines += [
            f"# HELP {name}_brownout_exits_total rung exits, by rung",
            f"# TYPE {name}_brownout_exits_total counter",
        ]
        for r in BROWNOUT_RUNGS:
            lines.append(f'{name}_brownout_exits_total{{rung="{r}"}} '
                         f"{s['brownout']['exits'][r]}")
        lines += [
            f"# HELP {name}_overload_hedges_suppressed_total hedges "
            "suppressed by brownout rung >= 1",
            f"# TYPE {name}_overload_hedges_suppressed_total counter",
            f"{name}_overload_hedges_suppressed_total "
            f"{s['hedges_suppressed_total']}",
            f"# HELP {name}_overload_token_caps_total per-request "
            "max_tokens caps applied by brownout rung >= 2",
            f"# TYPE {name}_overload_token_caps_total counter",
            f"{name}_overload_token_caps_total "
            f"{s['token_caps_applied_total']}",
            f"# HELP {name}_overload_drain_rate observed completions "
            "per second (the Retry-After denominator)",
            f"# TYPE {name}_overload_drain_rate gauge",
            f"{name}_overload_drain_rate {s['drain_rate_per_s']}",
        ]
        return lines
