"""Dynamic micro-batching: concurrent requests -> efficient engine batches.

The missing piece between "one caller, one batch" inference and serving
heavy concurrent traffic (Clipper-style adaptive batching): callers submit
single-sample feeds and get ``concurrent.futures.Future``s back; ONE
background thread drains a bounded queue, groups up to ``max_batch_size``
requests within a ``max_delay_ms`` window, and runs them through the
bucketed ``InferenceEngine`` as one padded batch.

Operational semantics (each covered by tests/test_serving.py):

* admission control — the queue is bounded; a full queue rejects the
  submit with ``OverloadedError`` instead of buffering unboundedly
  (explicit backpressure beats silent latency collapse).
* deadlines — a per-request deadline (default from the batcher); a
  request whose deadline passed while queued fails with
  ``DeadlineExceededError`` without burning engine time.
* error isolation — invalid feeds are rejected synchronously BEFORE the
  queue (``InvalidRequestError``); an engine failure fails only that
  batch's futures, and the loop keeps serving.
* graceful drain — ``close()`` stops admissions (``ShutdownError``),
  finishes everything already queued, then joins the worker; ``close
  (drain=False)`` fails queued requests instead.  SIGTERM wiring lives in
  ``server.py``.
"""

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError

import jax
import numpy as np

from paddle_tpu.obs import trace as obstrace
from paddle_tpu.resilience import faults
from paddle_tpu.serving.engine import InvalidRequestError, _np_leaf
from paddle_tpu.utils.logging import logger


class OverloadedError(RuntimeError):
    """Bounded request queue is full — the server is over capacity; retry
    with backoff (HTTP 429)."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before it reached the engine."""


class ShutdownError(RuntimeError):
    """The batcher is draining/closed; no new requests are admitted."""


class BatchExecutionError(RuntimeError):
    """The engine failed while executing the batch holding this request
    (cause chained); other batches are unaffected."""


class _Request:
    __slots__ = ("feed", "future", "deadline", "t_submit", "queue_span")

    def __init__(self, feed, deadline):
        self.feed = feed
        self.future = Future()
        self.deadline = deadline          # absolute perf_counter() or None
        self.t_submit = time.perf_counter()
        # async-seam span (obs/trace.py): submit() starts it AFTER the
        # request is actually enqueued (a rejected submit must not leak
        # a forever-active span); the worker ends it at batch pickup —
        # the queue wait made visible
        self.queue_span = obstrace.NULL

    def fail(self, exc):
        """Resolve with an exception, tolerating a client-side cancel that
        raced us — an InvalidStateError here must never kill the worker."""
        self.queue_span.end()       # idempotent; a request failed while
        #                             still queued must not leak its span
        try:
            self.future.set_exception(exc)
        except InvalidStateError:
            pass


class Batcher:
    """Bounded-queue dynamic batcher in front of an ``InferenceEngine``.

    max_batch_size: largest batch formed (default: the engine's top
    bucket).  max_delay_ms: how long the first request of a batch may wait
    for co-riders; 0 batches only what is already queued.  queue_size:
    admission bound.  default_deadline_ms: per-request deadline when the
    submit names none (None/0 = no deadline).
    """

    def __init__(self, engine, max_batch_size=None, max_delay_ms=5.0,
                 queue_size=256, default_deadline_ms=None, name=None):
        self.engine = engine
        self.metrics = engine.metrics
        self.max_batch_size = int(max_batch_size or engine.buckets[-1])
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.default_deadline_s = (float(default_deadline_ms) / 1e3
                                   if default_deadline_ms else None)
        if int(queue_size) < 1:
            # queue.Queue(0) would mean UNBOUNDED — silently disabling the
            # admission control this class exists to provide
            raise ValueError("queue_size must be >= 1")
        self._q = queue.Queue(maxsize=int(queue_size))
        self._depth_fn = self._q.qsize
        self.metrics.queue_depth_fns.append(self._depth_fn)
        self._closed = threading.Event()
        # makes {closed-check + enqueue} atomic against close(): without
        # it a submit could slip its request into the queue after the
        # drain finished, leaving its future unresolved forever
        self._admit_lock = threading.Lock()
        self.name = name or f"batcher[{engine.name}]"
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name)
        self._thread.start()

    # ------------------------------------------------------------ submit

    def submit(self, feed_row, deadline_ms=None):
        """Admit one single-sample feed (leaves WITHOUT a batch axis —
        the batcher stacks rows); returns a Future resolving to the
        per-row output pytree (numpy leaves).

        Raises synchronously: ``InvalidRequestError`` (spec mismatch —
        checked before queueing so a malformed request can never poison a
        batch), ``OverloadedError`` (queue full), ``ShutdownError``
        (draining)."""
        # fault point FIRST: an injected submit failure provably mutated
        # nothing, so retry_transient's idempotence guarantee holds
        faults.hit("batcher.submit")
        if self._closed.is_set():
            self.metrics.reject("shutdown")
            raise ShutdownError(f"{self.name} is draining; submit rejected")
        try:
            self.engine.validate(feed_row, batch=False)
        except InvalidRequestError:
            self.metrics.reject("invalid")
            raise
        dl_s = (float(deadline_ms) / 1e3 if deadline_ms
                else self.default_deadline_s)
        req = _Request(feed_row,
                       time.perf_counter() + dl_s if dl_s else None)
        # start the queue-wait span before the enqueue (the worker may
        # pull the request the instant it lands); the rejection paths
        # below end it so a refused submit leaks nothing
        # root=False: driven without an HTTP request span (bench drives,
        # embedded use) this must not mint a "request" for slowest()
        req.queue_span = obstrace.start_span("batcher.queue_wait",
                                             root=False)
        with self._admit_lock:
            if self._closed.is_set():   # close() raced the check above
                req.queue_span.end()
                self.metrics.reject("shutdown")
                raise ShutdownError(
                    f"{self.name} is draining; submit rejected")
            try:
                self._q.put_nowait(req)
            except queue.Full:
                req.queue_span.end()
                self.metrics.reject("overload")
                raise OverloadedError(
                    f"{self.name}: queue full ({self._q.maxsize} waiting)") \
                    from None
        self.metrics.accepted()
        return req.future

    def infer_one(self, feed_row, timeout=None, deadline_ms=None):
        """submit() + block for the result (the HTTP handler's path)."""
        return self.submit(feed_row, deadline_ms=deadline_ms).result(timeout)

    # ------------------------------------------------------------ worker

    def _loop(self):
        while True:
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._closed.is_set():
                    return
                continue
            batch = [first]
            # batch formation window: from the FIRST rider's pickup, wait
            # up to max_delay for co-riders, but never once full
            t_close = time.perf_counter() + self.max_delay_s
            while len(batch) < self.max_batch_size:
                wait = t_close - time.perf_counter()
                # draining: take whatever is queued, never wait for more
                if self._closed.is_set():
                    wait = 0.0
                try:
                    batch.append(self._q.get(timeout=max(wait, 0.0))
                                 if wait > 0 else self._q.get_nowait())
                except queue.Empty:
                    break
            self._run_batch(batch)

    def _run_batch(self, batch):
        now = time.perf_counter()
        live = []
        for r in batch:
            r.queue_span.end(batch_size=len(batch))
            if r.deadline is not None and now > r.deadline:
                self.metrics.reject("deadline")
                r.fail(DeadlineExceededError(
                    f"deadline exceeded after "
                    f"{(now - r.t_submit) * 1e3:.1f}ms in queue"))
                continue
            # atomically move PENDING -> RUNNING: a client cancel() from
            # here on returns False, so set_result below cannot race it;
            # False means the future was already cancelled — drop it
            if not r.future.set_running_or_notify_cancel():
                continue
            live.append(r)
        if not live:
            return
        try:
            stacked = jax.tree_util.tree_map(
                lambda *ls: np.stack([_np_leaf(l) for l in ls], axis=0),
                *[r.feed for r in live])
            # batch-assembly span: one per executed batch (the worker
            # thread has no request context; root=False keeps it out of
            # the slowest-requests table)
            with obstrace.span("batcher.batch", root=False, n=len(live)):
                out = self.engine.infer(stacked)    # host numpy leaves
        except Exception as e:    # noqa: BLE001 — isolate to THIS batch
            logger.warning("%s: batch of %d failed: %s: %s", self.name,
                           len(live), type(e).__name__, e)
            self.metrics.observe_error(len(live))
            for r in live:
                r.fail(BatchExecutionError(
                    f"batch execution failed: {type(e).__name__}: {e}"))
            return
        t_done = time.perf_counter()
        for i, r in enumerate(live):
            row = jax.tree_util.tree_map(lambda l, i=i: l[i], out)
            self.metrics.observe_response(t_done - r.t_submit)
            r.future.set_result(row)

    # ------------------------------------------------------------ shutdown

    def close(self, drain=True, timeout=30.0):
        """Stop admissions, then either finish the queue (drain=True) or
        fail queued requests with ``ShutdownError``.  Idempotent."""
        with self._admit_lock:      # no submit can race past this point
            self._closed.set()
        # stop contributing to a (possibly shared, longer-lived) metrics
        # object's queue depth — a closed batcher's queue is not backlog
        try:
            self.metrics.queue_depth_fns.remove(self._depth_fn)
        except ValueError:
            pass                    # already removed (idempotent close)
        if not drain:
            while True:
                try:
                    r = self._q.get_nowait()
                except queue.Empty:
                    break
                self.metrics.reject("shutdown")
                r.fail(ShutdownError("batcher closed without drain"))
        self._thread.join(timeout)
        if self._thread.is_alive():
            logger.warning("%s: worker did not drain within %.0fs",
                           self.name, timeout)
        # backstop: a request admitted in the instant between the worker's
        # final empty poll and its closed-check is still in the queue now
        # — fail it rather than strand its caller forever
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            self.metrics.reject("shutdown")
            r.fail(ShutdownError("batcher closed"))

    @property
    def closed(self):
        return self._closed.is_set()

    @property
    def ready(self):
        """Readiness (/readyz): accepting work AND the engine's ladder
        is warm (no request can pay a compile or hit a drain)."""
        return not self._closed.is_set() and self.engine.ready

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
