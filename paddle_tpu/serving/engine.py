"""Bucketed AOT inference engine — the execution half of the serving runtime.

TPU serving wants static shapes: one XLA executable per batch-size bucket,
compiled BEFORE traffic arrives, with every request batch padded up to the
nearest bucket and results sliced back.  ``InferenceEngine`` owns exactly
that ladder, for either execution source:

* in-process — a jit-traceable forward (``from_inferencer`` /
  ``from_topology``) AOT-compiled per bucket via the same
  ``jit(fn).lower(spec).compile()`` idiom as ``SGD.precompile``/
  ``SGD.lower_step``; ``lower(bucket)`` exposes the ``jax.stages.Lowered``
  so the analytic perf layer (``paddle_tpu/perf``) can read XLA's cost
  model per bucket without executing anything.
* exported artifacts — one serialized StableHLO file per bucket
  (``export.export_bucketed`` writes ``model.b{N}.shlo``;
  ``from_artifacts`` loads the ladder), each wrapped in ``jax.jit`` so the
  call compiles once per bucket and then dispatches.

Trace discipline mirrors the trainer: ``trace_count`` increments whenever
the forward's Python body runs under tracing, ``warmup()`` asserts one
trace per bucket, and steady-state serving cannot retrace by construction
(requests only ever execute at ladder shapes).  The ``lower()`` analytic
hook does trace (it re-stages the function); it is an offline tool, not a
serving path.

Batches larger than the top bucket are served by chunking at the top
bucket; numerics are row-independent (the forward is applied per row), so
padding and chunking change nothing about any real row's result.
"""

import re
import threading
import time

import numpy as np
import jax

from paddle_tpu.resilience import faults
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.utils.error import ConfigError
from paddle_tpu.utils.logging import logger
from paddle_tpu.utils.stats import timer

DEFAULT_BUCKETS = (1, 4, 16, 64)

# export_bucketed's documented naming convention, parsed by from_artifacts
ARTIFACT_RE = re.compile(r"\.b(\d+)\.shlo$")


class InvalidRequestError(ValueError):
    """Feed does not match the engine's input spec (shape/dtype/slots) —
    raised BEFORE the request reaches the batching queue."""


def _leaves(tree):
    return jax.tree_util.tree_flatten(tree)


def _np_leaf(leaf):
    return leaf if isinstance(leaf, np.ndarray) else np.asarray(leaf)


def _pad_rows(tree, n):
    """Pad every leaf's leading (batch) axis up to n by replicating the
    last real row — replication keeps padding numerically in-range for any
    model (zeros can be out-of-vocabulary for an id feed)."""
    def pad(leaf):
        leaf = _np_leaf(leaf)
        b = leaf.shape[0]
        if b == n:
            return leaf
        reps = np.repeat(leaf[-1:], n - b, axis=0)
        return np.concatenate([leaf, reps], axis=0)
    return jax.tree_util.tree_map(pad, tree)


def _slice_rows(tree, n):
    # numpy slicing on host-materialized outputs: a jnp slice here would
    # stage a NEW XLA computation per (bucket, real-rows) shape pair —
    # ~100ms compile on every previously unseen occupancy
    return jax.tree_util.tree_map(lambda l: l[:n], tree)


def _concat_rows(trees):
    if len(trees) == 1:
        return trees[0]
    return jax.tree_util.tree_map(lambda *ls: np.concatenate(ls, axis=0),
                                  *trees)


class InferenceEngine:
    """Dynamic-batching execution engine over a bucket ladder.

    Build with one of the factories:
      ``from_inferencer(inferencer, feed_spec, buckets=...)``
      ``from_topology(output_layer, parameters, feed_spec, ...)``
      ``from_artifact(path)`` / ``from_artifacts(glob_pattern)``

    ``feed_spec``: one feed dict whose leaves carry a LEADING batch axis
    (any size — it is replaced per bucket); leaves may be example arrays,
    ``jax.ShapeDtypeStruct``s, or SequenceBatch-wrapped versions.

    ``warm=True`` compiles every bucket up front (serving startup);
    ``warm=False`` compiles each bucket on first use (the v2 in-process
    path, where paying the whole ladder eagerly would be waste).
    """

    def __init__(self, *, jitted=None, feed_spec=None, artifacts=None,
                 buckets=DEFAULT_BUCKETS, warm=True, name="model",
                 metrics=None, trace_box=None):
        self.name = name
        self.metrics = metrics or ServingMetrics()
        self._lock = threading.Lock()   # executable table + compile serial
        self._compiled = {}             # bucket -> executable(feed)
        self._trace_box = trace_box if trace_box is not None else [0]
        self._artifacts = None
        if (jitted is None) == (artifacts is None):
            raise ConfigError("InferenceEngine: exactly one of jitted= or "
                              "artifacts= must be given (use the from_* "
                              "factories)")
        if artifacts is not None:
            # {bucket: jax.export.Exported}
            self._artifacts = dict(artifacts)
            self.buckets = tuple(sorted(self._artifacts))
            spec = _artifact_feed_spec(self._artifacts[self.buckets[0]])
        else:
            self._jit = jitted
            self.buckets = tuple(sorted(set(int(b) for b in buckets)))
            if not self.buckets or self.buckets[0] < 1:
                raise ConfigError(f"bad bucket ladder {buckets!r}")
            spec = feed_spec
        if spec is None:
            raise ConfigError("InferenceEngine needs a feed_spec")
        self._set_row_spec(spec)
        if warm:
            self.warmup()

    # ------------------------------------------------------------ factories

    @classmethod
    def from_inferencer(cls, inferencer, feed_spec, buckets=DEFAULT_BUCKETS,
                        warm=True, name="model", metrics=None):
        """Wrap an in-process ``trainer.Inferencer`` (params/state/quantize
        already resolved there) in a bucket ladder."""
        trace_box = [0]

        def fwd(feed):
            trace_box[0] += 1       # runs only under tracing
            return inferencer._fwd(inferencer._exec_params,
                                   inferencer.model_state, feed)

        return cls(jitted=jax.jit(fwd), feed_spec=feed_spec,
                   buckets=buckets, warm=warm, name=name, metrics=metrics,
                   trace_box=trace_box)

    @classmethod
    def from_topology(cls, output_layer, parameters, feed_spec,
                      model_state=None, buckets=DEFAULT_BUCKETS, warm=True,
                      compute_dtype=None, quantize=None, name="model",
                      metrics=None):
        from paddle_tpu.trainer.trainer import Inferencer
        inf = Inferencer(output_layer, parameters, model_state=model_state,
                         compute_dtype=compute_dtype, quantize=quantize)
        return cls.from_inferencer(inf, feed_spec, buckets=buckets,
                                   warm=warm, name=name, metrics=metrics)

    @classmethod
    def from_artifact(cls, path_or_bytes, warm=True, name=None,
                      metrics=None):
        """One exported StableHLO artifact -> a one-bucket engine (the
        bucket is the artifact's baked batch size)."""
        from paddle_tpu.export import load_inference
        exp = load_inference(path_or_bytes).exported
        bucket = _artifact_batch(exp)
        return cls(artifacts={bucket: exp}, warm=warm,
                   name=name or "artifact", metrics=metrics)

    @classmethod
    def from_artifacts(cls, pattern, warm=True, name=None, metrics=None):
        """Load a bucket ladder written by ``export.export_bucketed``:
        ``pattern`` is a glob (or explicit list of paths) of
        ``<prefix>.b{N}.shlo`` files; N (from the filename, cross-checked
        against the baked batch dim) keys the ladder."""
        import glob as _glob
        from paddle_tpu.export import load_inference
        paths = (sorted(_glob.glob(pattern)) if isinstance(pattern, str)
                 else sorted(pattern))
        if not paths:
            raise ConfigError(f"from_artifacts: nothing matches {pattern!r}")
        arts = {}
        for p in paths:
            m = ARTIFACT_RE.search(p)
            if not m:
                raise ConfigError(
                    f"from_artifacts: {p!r} does not follow the "
                    "<prefix>.b{N}.shlo naming convention "
                    "(export.export_bucketed writes it)")
            n = int(m.group(1))
            exp = load_inference(p).exported
            baked = _artifact_batch(exp)
            if baked != n:
                raise ConfigError(
                    f"from_artifacts: {p!r} names bucket {n} but its baked "
                    f"batch dim is {baked}")
            arts[n] = exp
        return cls(artifacts=arts, warm=warm, name=name or "artifacts",
                   metrics=metrics)

    # ------------------------------------------------------------ spec

    def _set_row_spec(self, feed_spec):
        """Normalize the batch-leading feed spec into a per-row signature
        (treedef + per-leaf trailing shape/dtype) used for validation and
        per-bucket ShapeDtypeStruct construction."""
        def aval(leaf):
            if isinstance(leaf, jax.ShapeDtypeStruct):
                return leaf
            a = np.asarray(leaf)
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        spec = jax.tree_util.tree_map(aval, feed_spec)
        leaves, treedef = _leaves(spec)
        for l in leaves:
            if len(l.shape) < 1:
                raise ConfigError(
                    "feed_spec leaves need a leading batch axis; got "
                    f"scalar {l}")
        self._treedef = treedef
        self._row_sig = tuple((tuple(l.shape[1:]), np.dtype(l.dtype))
                              for l in leaves)

    def bucket_spec(self, bucket):
        """The feed pytree of ``ShapeDtypeStruct``s for one bucket."""
        leaves = [jax.ShapeDtypeStruct((bucket,) + shape, dtype)
                  for shape, dtype in self._row_sig]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def validate(self, feed, batch=True):
        """Shape/dtype-check a feed against the engine spec; raises
        ``InvalidRequestError``.  batch=True expects a leading batch axis
        (equal across leaves); batch=False expects bare per-row leaves.
        Returns the batch size (or 1 for rows)."""
        try:
            leaves, treedef = _leaves(feed)
        except Exception as e:    # noqa: BLE001 — unflattenable input
            raise InvalidRequestError(f"unreadable feed: {e}") from e
        if treedef != self._treedef:
            raise InvalidRequestError(
                f"feed structure {treedef} != engine spec {self._treedef}")
        b = None
        for leaf, (shape, dtype) in zip(leaves, self._row_sig):
            a = _np_leaf(leaf)
            if batch:
                if a.ndim != len(shape) + 1 or tuple(a.shape[1:]) != shape:
                    raise InvalidRequestError(
                        f"leaf shape {a.shape} != [B]+{list(shape)}")
                if b is None:
                    b = a.shape[0]
                elif a.shape[0] != b:
                    raise InvalidRequestError(
                        f"inconsistent batch dims ({b} vs {a.shape[0]})")
            elif tuple(a.shape) != shape:
                raise InvalidRequestError(
                    f"row leaf shape {a.shape} != {list(shape)}")
            if np.dtype(a.dtype) != dtype:
                raise InvalidRequestError(
                    f"leaf dtype {a.dtype} != {dtype}")
        if batch and not b:
            raise InvalidRequestError("empty batch")
        return b if batch else 1

    # ------------------------------------------------------------ compile

    @property
    def trace_count(self):
        return self._trace_box[0]

    @property
    def ready(self):
        """Readiness (the /readyz half of health): every ladder bucket
        holds a warmed executable, so no request can pay a compile."""
        with self._lock:
            return all(b in self._compiled for b in self.buckets)

    def bucket_for(self, n):
        """Smallest bucket >= n, or None when n exceeds the ladder top."""
        for b in self.buckets:
            if b >= n:
                return b
        return None

    def _exec_for(self, bucket):
        with self._lock:
            fn = self._compiled.get(bucket)
            if fn is not None:
                return fn
            t0 = time.perf_counter()
            if self._artifacts is not None:
                fn = jax.jit(self._artifacts[bucket].call)
            else:
                fn = self._jit.lower(self.bucket_spec(bucket)).compile()
            self._compiled[bucket] = fn
            logger.info("serving[%s]: bucket %d ready in %.2fs", self.name,
                        bucket, time.perf_counter() - t0)
            return fn

    def warmup(self):
        """Compile AND execute every ladder bucket once (on zeros) before
        traffic — the first execution of a fresh executable pays one-time
        runtime setup (~100ms-class even on CPU) that must never land on a
        live request.  In-process engines additionally assert the
        per-bucket trace discipline: each NEW bucket costs exactly one
        trace of the forward's Python body, and steady-state serving costs
        zero (``trace_count`` stays flat).  Returns the number of newly
        compiled buckets."""
        from paddle_tpu.testing.trace import expect_traces
        n_new = 0
        for b in self.buckets:
            if b in self._compiled:
                continue
            n_new += 1
            zeros = jax.tree_util.tree_map(
                lambda l: np.zeros(l.shape, l.dtype), self.bucket_spec(b))

            def _compile_and_run(b=b, zeros=zeros):
                jax.block_until_ready(self._exec_for(b)(zeros))

            if self._artifacts is None:
                with expect_traces(lambda: self.trace_count, 1,
                                   f"serving[{self.name}]: bucket {b} "
                                   "warm-up",
                                   hint="the forward is not shape-stable"):
                    _compile_and_run()
            else:
                _compile_and_run()
        if n_new:
            logger.info("serving[%s]: %d bucket executable(s) warm %s",
                        self.name, len(self._compiled), list(self.buckets))
        return n_new

    def lower(self, bucket=None):
        """The ``jax.stages.Lowered`` for one bucket (default: the ladder
        top) — the ``extras["lower"]`` analytic hook: ``perf/analytic``
        compiles it on the CPU backend and reads XLA's cost model to
        predict per-bucket serving cost.  Offline tool: lowering re-stages
        the forward (one extra trace); artifacts cannot re-lower."""
        if self._artifacts is not None:
            raise ConfigError(
                "lower(): an artifact-backed engine holds serialized "
                "StableHLO, not a traceable forward; run the analytic "
                "layer against the in-process engine that exported it")
        bucket = int(bucket) if bucket is not None else self.buckets[-1]
        return self._jit.lower(self.bucket_spec(bucket))

    # ------------------------------------------------------------ execute

    def infer(self, feed):
        """Serve one request batch: validate, pad to the nearest bucket
        (chunking at the ladder top when the batch exceeds it), execute,
        slice the real rows back.  Returns the output pytree with HOST
        numpy leaves (serving results leave the device).  Row results are
        independent of padding and co-batched rows."""
        b = self.validate(feed, batch=True)
        top = self.buckets[-1]
        if b > top:
            chunks = []
            for lo in range(0, b, top):
                n = min(top, b - lo)
                chunks.append(self._infer_bucketed(
                    jax.tree_util.tree_map(
                        lambda l: _np_leaf(l)[lo:lo + n], feed), n))
            return _concat_rows(chunks)
        return self._infer_bucketed(feed, b)

    def _infer_bucketed(self, feed, b):
        bucket = self.bucket_for(b)
        fn = self._exec_for(bucket)
        faults.hit("serving.engine.execute")
        t0 = time.perf_counter()
        with timer("serving_batch"):
            out = fn(_pad_rows(feed, bucket))
            # materialize to host here: serving results leave the device
            # anyway, and host-side numpy slicing is free while a device
            # slice would compile per occupancy (see _slice_rows)
            out = jax.device_get(out)
        self.metrics.observe_batch(b, bucket, time.perf_counter() - t0)
        return _slice_rows(out, b)


# ---------------------------------------------------------------- artifacts


def _artifact_feed_tree(exp):
    """Exported -> its feed pytree of avals.  ``export_inference`` exports
    functions of ONE positional feed argument; the in_tree is ((feed,), {})."""
    tree = jax.tree_util.tree_unflatten(exp.in_tree, list(exp.in_avals))
    args, kwargs = tree
    if kwargs or len(args) != 1:
        raise ConfigError(
            "artifact does not take a single feed argument (was it written "
            "by export_inference/export_bucketed?)")
    return args[0]


def _artifact_feed_spec(exp):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
        _artifact_feed_tree(exp))


def _artifact_batch(exp):
    leaves, _ = _leaves(_artifact_feed_tree(exp))
    dims = {l.shape[0] for l in leaves if len(l.shape)}
    if len(dims) != 1:
        raise ConfigError(
            f"artifact input batch dims disagree: {sorted(dims)}")
    return int(dims.pop())
