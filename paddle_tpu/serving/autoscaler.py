"""Trace-driven autoscaler: the control loop that sizes the replica
fleet to its SLO (docs/serving.md §8).

Everything below it already exists: PR 7's ``ReplicaSupervisor`` can
spawn a replica to readiness and drain one out with zero failed
requests; PR 9's metrics surface says exactly where the latency is.
What was missing is the loop that ACTS on that evidence — fleet size was
``--replicas N``, chosen by an operator, wrong the moment load changed.
This module closes ROADMAP item 5's loop: SLOs held by control law, not
by provisioning.

The law is deliberately boring (target tracking with hysteresis — the
thing that actually works in production autoscalers):

* SIGNAL — each poll reads the router's live surface: recent-window
  TTFT p99 (``RouterMetrics.slo_p99_recent_s``), per-replica readiness/
  queue depth/in-flight (``Router.replica_states``), breaker states.
  No new instrumentation; the PR 9 surface IS the sensor.
* LAW — a dead band around ``target_ttft_ms``: p99 above
  ``target*(1+hysteresis)`` for ``breach_polls`` consecutive polls →
  scale OUT; p99 below ``target*(1-hysteresis)`` with an empty queue
  for ``slack_polls`` polls → scale IN.  Per-direction cooldowns gate
  actuation (out reacts in seconds, in waits a minute), and min/max
  bounds are hard.
* ACTUATION — scale-out is ``supervisor.add_replica()`` then
  spawn-TO-READINESS: the new replica counts toward capacity only once
  it answers /readyz; one that never does is removed and the attempt
  retried with seeded exponential backoff (the ``fleet.spawn`` and
  ``autoscaler.scale`` fault points make this a replayable chaos
  case).  Scale-in drains the least-loaded replica — and NEVER one
  holding active streams while an idle one exists — through the same
  rolling ``drain()`` PR 7 proved loses zero requests.
* EVIDENCE — every decision is journaled (a bounded ring of dicts that
  replays bit-for-bit given the same signals, seed, and clock), traced
  (``autoscaler.decision`` / ``autoscaler.scale`` events, obs/trace.py)
  and counted (``autoscaler_*`` lines appended to the router's
  /metrics).

The loop takes an injectable monotonic ``clock`` and a seeded rng for
poll jitter + retry backoff, so tests (tests/test_autoscaler.py) drive
it tick-by-tick on a simulated clock and the full decision log is
deterministic.

CLI (``python -m paddle_tpu.serving.autoscaler``):
  --min-replicas/--max-replicas --target-ttft-ms ...   run a managed
      fleet + router + autoscaler (the production shape)
  --smoke   self-test (healthy_window.sh phase 14): 1 replica + a
      seeded load spike → scale-out to 2 and p99 TTFT back under
      target, spike ends → rolling scale-in, ZERO failed requests;
      ONE JSON line, exit code.
"""

import argparse
import json
import math
import random
import signal
import sys
import threading
import time

from paddle_tpu.obs import trace as obstrace
from paddle_tpu.resilience import faults
from paddle_tpu.utils.logging import logger

DECISIONS = ("out", "in", "hold")


class Autoscaler:
    """Target-tracking control loop over a ``ReplicaSupervisor`` +
    ``Router`` pair.  All tuning knobs default from utils/flags.py
    (``autoscaler_*``)."""

    def __init__(self, supervisor, router, poll_interval_s=None,
                 target_ttft_ms=None, hysteresis=None, breach_polls=None,
                 slack_polls=None, cooldown_out_s=None, cooldown_in_s=None,
                 min_replicas=None, max_replicas=None, window_s=None,
                 seed=None, ready_timeout_s=240.0, drain_timeout_s=60.0,
                 retry_base_s=0.5, retry_max_s=10.0, retry_max_attempts=8,
                 journal_cap=4096, clock=None, name="autoscaler"):
        from paddle_tpu.utils.flags import FLAGS

        def _f(v, flag):
            return getattr(FLAGS, flag) if v is None else v

        self.supervisor = supervisor
        self.router = router
        self.poll_interval_s = float(_f(poll_interval_s,
                                        "autoscaler_poll_interval_s"))
        self.target_s = float(_f(target_ttft_ms,
                                 "autoscaler_target_ttft_ms")) / 1e3
        self.hysteresis = float(_f(hysteresis, "autoscaler_hysteresis"))
        self.breach_polls = int(_f(breach_polls, "autoscaler_breach_polls"))
        self.slack_polls = int(_f(slack_polls, "autoscaler_slack_polls"))
        self.cooldown_out_s = float(_f(cooldown_out_s,
                                       "autoscaler_cooldown_out_s"))
        self.cooldown_in_s = float(_f(cooldown_in_s,
                                      "autoscaler_cooldown_in_s"))
        self.min_replicas = int(_f(min_replicas,
                                   "autoscaler_min_replicas"))
        self.max_replicas = int(_f(max_replicas,
                                   "autoscaler_max_replicas"))
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})")
        self.window_s = float(_f(window_s, "autoscaler_window_s"))
        self.ready_timeout_s = float(ready_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.retry_base_s = float(retry_base_s)
        self.retry_max_s = float(retry_max_s)
        self.retry_max_attempts = int(retry_max_attempts)
        self.name = name
        self.clock = clock or time.monotonic
        # ONE seeded stream drives poll jitter and retry backoff, in
        # tick order — the reason the whole decision log replays
        self._rng = random.Random(int(_f(seed, "autoscaler_seed")))
        self._lock = threading.Lock()
        # control state.  Cooldowns anchor on the LAST SCALE OF ANY
        # DIRECTION, gated by the acting direction's own cooldown — the
        # flap-damping semantics the acceptance bar wants ("the replica
        # count changes at most once per cooldown window"): a scale-in
        # cannot fire within cooldown_in_s of the scale-out it would
        # undo, and vice versa.
        self._breach_streak = 0
        self._slack_streak = 0
        self._last_change = -math.inf
        self._retry = None              # {"direction","at","k"} pending
        self._tick = 0
        # evidence
        self.journal = []               # bounded decision ring
        self.journal_cap = int(journal_cap)
        self.decisions_total = {d: 0 for d in DECISIONS}
        self.scales_total = {"out": 0, "in": 0}
        self.scale_failures_total = 0
        self.last_signals = {}
        self._closed = threading.Event()
        self._thread = None
        # contribute autoscaler_* lines to the router's /metrics page
        router.extra_render_fns.append(self.render_lines)

    # ------------------------------------------------------------ signals

    def collect(self):
        """One reading of the PR 9 surface: fleet-wide recent-window
        TTFT p99 plus the router's live per-replica view.  Pure read —
        collect() never mutates control state."""
        states = self.router.replica_states()
        ready = sorted(rid for rid, st in states.items()
                       if st["ready"] and st["breaker"] != "open")
        loads = {rid: st["queue_depth"] + st["inflight"]
                 for rid, st in states.items()}
        p99_s = self.router.metrics.slo_p99_recent_s(self.window_s)
        return {
            # None = no completion landed inside the window (idle fleet
            # OR total stall — decide() disambiguates via queue/inflight)
            "ttft_p99_ms": round(p99_s * 1e3, 3)
            if p99_s is not None else None,
            "replicas": len(self.supervisor.replicas),
            "ready_replicas": len(ready),
            "ready": ready,
            "loads": loads,
            "queue_depth": sum(st["queue_depth"]
                               for st in states.values()),
            "inflight": sum(st["inflight"] for st in states.values()),
            "breakers_open": sorted(rid for rid, st in states.items()
                                    if st["breaker"] == "open"),
        }

    # ------------------------------------------------------------ the law

    def decide(self, sig, now):
        """The pure control law: (decision, reason).  Deterministic in
        (signals, control state, now) — no clock reads, no randomness —
        so a journal replays bit-for-bit."""
        n_total = sig["replicas"]
        p99_s = (sig["ttft_p99_ms"] / 1e3
                 if sig["ttft_p99_ms"] is not None else None)
        high = self.target_s * (1.0 + self.hysteresis)
        low = self.target_s * (1.0 - self.hysteresis)
        if self._retry is not None:
            # a failed actuation owns the loop — but it must not outlive
            # the conditions that justified it: a retry is ABANDONED
            # when the bounds no longer allow the direction, when the
            # signal has swung to the opposite band (the spike ended
            # while the spawn was failing), or after retry_max_attempts
            # (the law then re-decides from fresh streaks)
            d = self._retry["direction"]
            abandon = (
                self._retry["k"] > self.retry_max_attempts
                or (d == "out" and (n_total >= self.max_replicas
                                    or (p99_s is not None
                                        and p99_s < low)))
                or (d == "in" and (n_total <= self.min_replicas
                                   or (p99_s is not None
                                       and p99_s > high))))
            if abandon:
                self._retry = None
                self._breach_streak = 0     # demand fresh evidence
                self._slack_streak = 0
            elif now >= self._retry["at"]:
                return d, (f"retry #{self._retry['k']} after failed "
                           f"scale-{d}")
            else:
                return "hold", "awaiting actuation retry backoff"
        if p99_s is None:
            # NO SIGNAL in the window.  A truly idle fleet (no queued or
            # in-flight work) is slack — shrink it; anything else could
            # be a total stall where nothing completes, which must never
            # read as 'healthy 0ms'
            breach = False
            slack = sig["queue_depth"] == 0 and sig["inflight"] == 0
        else:
            breach = p99_s > high
            # slack does NOT require zero in-flight work: an over-
            # provisioned fleet that is merely busy must still shrink —
            # the victim choice (idle-preferred) and the graceful drain
            # make that safe
            slack = p99_s < low and sig["queue_depth"] == 0
        if breach:
            self._breach_streak += 1
            self._slack_streak = 0
        elif slack:
            self._slack_streak += 1
            self._breach_streak = 0
        else:
            self._breach_streak = 0
            self._slack_streak = 0
        if (self._breach_streak >= self.breach_polls
                and n_total < self.max_replicas
                and now - self._last_change >= self.cooldown_out_s):
            return "out", (f"ttft_p99 {sig['ttft_p99_ms']:.0f}ms > "
                           f"{high * 1e3:.0f}ms for "
                           f"{self._breach_streak} polls")
        if (self._slack_streak >= self.slack_polls
                and n_total > self.min_replicas
                and now - self._last_change >= self.cooldown_in_s):
            p99_txt = (f"{sig['ttft_p99_ms']:.0f}ms" if sig["ttft_p99_ms"]
                       is not None else "no-signal (fleet idle)")
            return "in", (f"ttft_p99 {p99_txt} < {low * 1e3:.0f}ms for "
                          f"{self._slack_streak} polls")
        # blocked decisions journal WHY they held — the replayable
        # evidence must distinguish "healthy" from "breaching but
        # damped" during an incident
        if self._breach_streak >= self.breach_polls:
            if n_total >= self.max_replicas:
                return "hold", "breach but at max_replicas"
            return "hold", (f"breach ({self._breach_streak} polls) "
                            "cooling down after the last scale")
        if self._slack_streak >= self.slack_polls:
            if n_total <= self.min_replicas:
                return "hold", "slack but at min_replicas"
            return "hold", (f"slack ({self._slack_streak} polls) "
                            "cooling down after the last scale")
        return "hold", "inside the dead band"

    # --------------------------------------------------------- actuation

    def _pick_victim(self, sig):
        """Scale-in victim, in order of preference: (1) a replica that
        is NOT serving (dead, backoff, storm-tripped — removing broken
        capacity is the cheapest scale-in, and draining the only
        HEALTHY replica while a corpse stays counted would be an
        outage); (2) an IDLE ready replica — one holding active
        generation slots is never drained while an idle one exists (its
        streams would ride the failover path for no reason); (3) the
        least-loaded ready replica (the graceful drain finishes its
        streams).  Only replicas the supervisor still owns are
        candidates: the router's view lags the fleet by up to a poll
        interval."""
        owned = set(self.supervisor.replicas)
        if not owned:
            return None
        ready = [r for r in sig["ready"] if r in owned]
        unready = sorted(owned - set(ready))
        if unready:
            return unready[0]
        cands = ready or sorted(owned)
        idle = [r for r in cands if sig["loads"].get(r, 0) == 0]
        pool = idle or cands
        return min(pool, key=lambda r: (sig["loads"].get(r, 0), r))

    def _schedule_retry(self, direction, now):
        k = (self._retry["k"] + 1) if self._retry is not None else 1
        delay = min(self.retry_base_s * (2 ** (k - 1)), self.retry_max_s)
        delay *= 0.5 + 0.5 * self._rng.random()     # seeded jitter
        self._retry = {"direction": direction, "at": now + delay, "k": k}
        self.scale_failures_total += 1
        return delay

    def actuate(self, direction, sig, now):
        """Execute one scale decision.  Returns an evidence dict for the
        journal.  Failures (the ``autoscaler.scale`` / ``fleet.spawn``
        fault points, a replica that never reaches readiness) schedule a
        seeded-backoff retry and leave capacity accounting untouched —
        an unready replica is REMOVED, never counted."""
        with obstrace.span("autoscaler.scale", root=False,
                           direction=direction):
            try:
                faults.hit("autoscaler.scale")
                if direction == "out":
                    rid = self.supervisor.add_replica()
                    if not self.supervisor.wait_ready(
                            timeout=self.ready_timeout_s, rids=(rid,)):
                        # spawned but never ready: it must not linger as
                        # phantom capacity
                        self.supervisor.remove_replica(
                            rid, drain_timeout=5.0)
                        raise RuntimeError(
                            f"{rid} not ready within "
                            f"{self.ready_timeout_s:.0f}s")
                    evidence = {"replica": rid, "ok": True}
                else:
                    rid = self._pick_victim(sig)
                    if rid is None:
                        raise RuntimeError("no drainable replica")
                    self.supervisor.remove_replica(
                        rid, drain_timeout=self.drain_timeout_s)
                    evidence = {"replica": rid, "ok": True}
            except Exception as e:    # noqa: BLE001 — actuation chaos
                delay = self._schedule_retry(direction, now)
                logger.warning(
                    "%s: scale-%s failed (%s: %s); retry #%d in %.2fs",
                    self.name, direction, type(e).__name__, e,
                    self._retry["k"], delay)
                return {"ok": False,
                        "error": f"{type(e).__name__}: {e}"[:200],
                        "retry_in_s": round(delay, 4)}
        self._retry = None
        self.scales_total[direction] += 1
        self._last_change = now
        self._breach_streak = 0     # fresh evidence at the new size
        self._slack_streak = 0
        logger.info("%s: scaled %s (%s); fleet now %d replica(s)",
                    self.name, direction.upper(), evidence["replica"],
                    len(self.supervisor.replicas))
        return evidence

    # ------------------------------------------------------------- loop

    def tick(self, now=None):
        """One control iteration: collect → decide → actuate → journal.
        Tests call this directly with a simulated ``now``; the
        background loop calls it on the jittered poll cadence."""
        with self._lock:
            now = self.clock() if now is None else now
            sig = self.collect()
            self.last_signals = sig
            decision, reason = self.decide(sig, now)
            entry = {"tick": self._tick, "t": round(now, 6),
                     "decision": decision, "reason": reason,
                     "signals": sig}
            self._tick += 1
            self.decisions_total[decision] += 1
            if decision in ("out", "in"):
                entry["actuation"] = self.actuate(decision, sig, now)
            self.journal.append(entry)
            if len(self.journal) > self.journal_cap:
                del self.journal[:len(self.journal) - self.journal_cap]
            obstrace.instant("autoscaler.decision", decision=decision,
                             reason=reason, ttft_p99_ms=sig["ttft_p99_ms"],
                             replicas=sig["replicas"])
            return entry

    def _loop(self):
        while not self._closed.is_set():
            try:
                self.tick()
            except Exception as e:    # noqa: BLE001 — the control loop
                # must outlive any one bad poll (a dying replica can make
                # collect() race a view teardown)
                logger.warning("%s: tick failed: %s: %s", self.name,
                               type(e).__name__, e)
            # seeded jitter de-synchronizes fleets of autoscalers without
            # giving up replayability (the rng is consumed in tick order)
            self._closed.wait(self.poll_interval_s
                              * (0.9 + 0.2 * self._rng.random()))

    def start(self):
        """Run the loop on a daemon thread (idempotent)."""
        if self._thread is None or not self._thread.is_alive():
            self._closed.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name=self.name)
            self._thread.start()
        return self

    def close(self):
        self._closed.set()
        if self._thread is not None:
            self._thread.join(5)
        # stop contributing to the router's /metrics: a replaced
        # autoscaler must not leave duplicate/stale autoscaler_* series
        # (and must not keep this instance reachable forever)
        try:
            self.router.extra_render_fns.remove(self.render_lines)
        except ValueError:
            pass                    # already removed (idempotent close)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ---------------------------------------------------------- evidence

    def snapshot(self):
        return {
            "replicas": len(self.supervisor.replicas),
            "decisions_total": dict(self.decisions_total),
            "scales_total": dict(self.scales_total),
            "scale_failures_total": self.scale_failures_total,
            "last_signals": dict(self.last_signals),
            "journal_len": len(self.journal),
        }

    def journal_lines(self):
        """The decision log as JSON lines (replayable evidence)."""
        return [json.dumps(e, sort_keys=True) for e in self.journal]

    def render_lines(self):
        """autoscaler_* Prometheus lines for the router's /metrics."""
        n = self.router.metrics.name
        s = self.snapshot()
        lines = [
            f"# HELP {n}_autoscaler_replicas supervised replicas",
            f"# TYPE {n}_autoscaler_replicas gauge",
            f"{n}_autoscaler_replicas {s['replicas']}",
            f"# HELP {n}_autoscaler_decisions_total control decisions, "
            "by direction",
            f"# TYPE {n}_autoscaler_decisions_total counter",
        ]
        for d in DECISIONS:
            lines.append(f'{n}_autoscaler_decisions_total'
                         f'{{direction="{d}"}} '
                         f"{s['decisions_total'][d]}")
        lines += [
            f"# HELP {n}_autoscaler_scales_total completed scale "
            "actuations, by direction",
            f"# TYPE {n}_autoscaler_scales_total counter",
        ]
        for d in ("out", "in"):
            lines.append(f'{n}_autoscaler_scales_total'
                         f'{{direction="{d}"}} {s["scales_total"][d]}')
        lines += [
            f"# HELP {n}_autoscaler_scale_failures_total failed "
            "actuations (retried with seeded backoff)",
            f"# TYPE {n}_autoscaler_scale_failures_total counter",
            f"{n}_autoscaler_scale_failures_total "
            f"{s['scale_failures_total']}",
            f"# HELP {n}_autoscaler_ttft_p99_ms last polled recent-"
            "window TTFT p99 (the tracked SLO signal; NaN = no sample "
            "completed inside the window)",
            f"# TYPE {n}_autoscaler_ttft_p99_ms gauge",
            f"{n}_autoscaler_ttft_p99_ms "
            f"{s['last_signals'].get('ttft_p99_ms') if s['last_signals'].get('ttft_p99_ms') is not None else 'NaN'}",
        ]
        return lines


# ------------------------------------------------------------------ smoke


def _smoke():
    """Autoscale self-test (healthy_window.sh phase 14): ONE tiny demo
    replica behind the router + autoscaler (min 1, max 2); a seeded load
    spike of concurrent paced streams breaches the TTFT target → the
    loop scales out to 2 and spawn-to-readiness completes; with both
    replicas serving, the post-scale drive's p99 TTFT sits back under
    target; the spike ends → sustained slack scales back in through the
    rolling drain.  EVERY request must either complete bit-identical to
    the local ``lm_generate`` oracle or be shed 429 with a Retry-After
    header — zero failed requests.  ONE JSON line; returns the exit
    code."""
    import http.client
    import numpy as _np
    import jax
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.fleet import ReplicaSupervisor
    from paddle_tpu.serving.router import Router

    errs = []
    out = {"metric": "autoscale smoke (trace-driven control loop: spike "
                     "-> scale-out -> recover -> scale-in)",
           "vs_baseline": None}
    vocab, max_len, n_tokens, slots = 256, 64, 12, 2
    n_spike_clients = 8
    target_ms = 600.0
    # the demo LM replica at 2 slots; the injected decode-step hang
    # paces tokens (~30ms each, ~0.4s per stream), so the 8-client
    # spike queues 3-4 streams deep per slot and the recent-window TTFT
    # p99 lands well above target*(1+hysteresis) while a 2-client
    # steady drive on the scaled fleet stays far below target
    extra = ["--gen-slots", str(slots), "--gen-max-len", str(max_len),
             "--gen-prefill-buckets", "8,16",
             "--gen-max-tokens", str(n_tokens),
             "--fault-spec",
             "serving.decode_step:every=1,action=hang,hang_s=0.03"]
    sup = ReplicaSupervisor(n_replicas=1, extra_args=extra,
                            backoff_base_s=0.3, seed=0,
                            name="autoscale_smoke")
    router = Router(supervisor=sup, poll_interval_s=0.1,
                    retry_budget=3, name="router_autoscale")
    scaler = Autoscaler(
        sup, router, poll_interval_s=0.25, target_ttft_ms=target_ms,
        hysteresis=0.2, breach_polls=2, slack_polls=10,
        cooldown_out_s=2.0, cooldown_in_s=4.0, min_replicas=1,
        max_replicas=2, window_s=6.0, seed=0, ready_timeout_s=240.0,
        name="autoscaler_smoke")
    httpd = None
    completed, shed, failed = [], [], []
    lock = threading.Lock()

    params = transformer.init(jax.random.PRNGKey(0), src_vocab=vocab,
                              trg_vocab=1, d_model=32, num_heads=2,
                              dff=64, enc_layers=2, dec_layers=0,
                              max_len=max_len)
    rng = _np.random.RandomState(0)
    prompts = [rng.randint(1, vocab, 3 + 2 * (i % 6)).astype(_np.int64)
               for i in range(n_spike_clients)]
    oracle = []
    for p in prompts:
        ids = _np.asarray(transformer.lm_generate(
            params, p[None], max_len=max_len, num_heads=2,
            prompt_lengths=_np.asarray([p.size])))
        oracle.append(ids[0, p.size:p.size + n_tokens].tolist())

    def one_stream(i, port):
        """One streaming request; records completion/shed/failure and
        returns the TTFT ms (None unless completed)."""
        t0 = time.perf_counter()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=120)
            conn.request("POST", "/v1/generate",
                         json.dumps({"prompt": prompts[i].tolist(),
                                     "max_tokens": n_tokens,
                                     "stream": True}).encode(),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status == 429:
                ra = resp.getheader("Retry-After")
                resp.read()
                conn.close()
                with lock:
                    shed.append({"retry_after": ra})
                if ra is None:
                    errs.append("shed response missing Retry-After")
                return None
            toks, ttft_ms, done = [], None, None
            while True:
                line = resp.readline()
                if not line:
                    break
                rec = json.loads(line)
                if "token" in rec:
                    if not toks:
                        ttft_ms = (time.perf_counter() - t0) * 1e3
                    toks.append(rec["token"])
                if rec.get("done"):
                    done = rec
                    break
            conn.close()
            if done is None or toks != oracle[i]:
                with lock:
                    failed.append({"i": i, "toks": toks[:4]})
                return None
            with lock:
                completed.append(ttft_ms)
            return ttft_ms
        except Exception as e:      # noqa: BLE001
            with lock:
                failed.append({"i": i, "err": f"{type(e).__name__}: {e}"})
            return None

    try:
        sup.start()
        if not sup.wait_ready(timeout=240):
            raise RuntimeError("seed replica never became ready")
        httpd = router.start(port=0)
        deadline = time.monotonic() + 30
        while not router.ready() and time.monotonic() < deadline:
            time.sleep(0.05)
        scaler.start()
        port = httpd.port

        # ---- SPIKE: n_spike_clients concurrent paced clients loop
        # until the scaler has brought the second replica to readiness
        # (bounded)
        spike_stop = threading.Event()
        spike_ttfts = []

        def spike_client(i):
            while not spike_stop.is_set():
                t = one_stream(i, port)
                if t is not None:
                    with lock:
                        spike_ttfts.append(t)

        threads = [threading.Thread(target=spike_client, args=(i,))
                   for i in range(n_spike_clients)]
        for t in threads:
            t.start()
        spike_deadline = time.monotonic() + 300
        while time.monotonic() < spike_deadline:
            if len(sup.replicas) >= 2 and sup.wait_ready(timeout=0.1):
                break
            time.sleep(0.2)
        scaled_out = len(sup.replicas) >= 2
        spike_stop.set()
        for t in threads:
            t.join(180)
        out["scaled_out"] = bool(scaled_out)
        out["spike_requests"] = len(completed) + len(shed)
        spike_p99 = (sorted(spike_ttfts)[int(0.99 * (len(spike_ttfts)
                                                     - 1))]
                     if spike_ttfts else None)
        out["spike_ttft_p99_ms"] = round(spike_p99, 1) \
            if spike_p99 is not None else None

        # ---- RECOVERED: with 2 replicas serving, a light steady drive
        # must sit back under the target
        steady = []
        for rep in range(3):
            ts = [threading.Thread(
                target=lambda i=i: steady.append(one_stream(i, port)))
                for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(120)
        steady_ok = [t for t in steady if t is not None]
        steady_p99 = (sorted(steady_ok)[int(0.99 * (len(steady_ok) - 1))]
                      if steady_ok else None)
        out["steady_ttft_p99_ms"] = round(steady_p99, 1) \
            if steady_p99 is not None else None
        recovered = steady_p99 is not None and steady_p99 < target_ms

        # ---- SLACK: traffic stops; sustained slack + cooldown scale
        # the fleet back in through the zero-failure rolling drain
        scale_in_deadline = time.monotonic() + 120
        while time.monotonic() < scale_in_deadline:
            if len(sup.replicas) <= 1:
                break
            time.sleep(0.2)
        scaled_in = len(sup.replicas) <= 1

        snap = scaler.snapshot()
        decisions = [e["decision"] for e in scaler.journal]
        import urllib.request
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
            mtext = r.read().decode()
        out.update(
            scaled_in=bool(scaled_in),
            recovered_under_target=bool(recovered),
            completed=len(completed),
            shed=len(shed),
            failed=len(failed),
            decisions_out=snap["scales_total"]["out"],
            decisions_in=snap["scales_total"]["in"],
            scale_failures=snap["scale_failures_total"],
            journal_len=snap["journal_len"],
            metrics_sane=("autoscaler_replicas" in mtext
                          and "autoscaler_scales_total" in mtext
                          and "overload_limit" in mtext),
        )
        checks = [
            scaled_out,
            recovered,
            scaled_in,
            len(failed) == 0 and len(completed) > 0,
            "out" in decisions and "in" in decisions,
            bool(out["metrics_sane"]),
        ]
        if failed:
            errs.append(f"failed requests: {failed[:3]}")
    except Exception as e:      # noqa: BLE001 — a harness failure must
        errs.append(f"smoke: {type(e).__name__}: {e}")
        checks = [False]
    finally:
        try:
            scaler.close()
            router.close()
        finally:
            sup.stop()
    out["value"] = sum(bool(c) for c in checks)
    out["unit"] = f"checks_ok/{len(checks)}"
    if errs:
        out["errors"] = errs[:5]
    print(json.dumps(out), flush=True)
    return 0 if all(checks) else 2


# -------------------------------------------------------------------- CLI


def main(argv=None):
    from paddle_tpu.utils.flags import FLAGS
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.autoscaler",
        description="trace-driven autoscaler over the replica fleet "
                    "(docs/serving.md §8)")
    ap.add_argument("--replica-arg", action="append", default=[],
                    help="extra argv appended to each managed replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=FLAGS.router_port)
    ap.add_argument("--min-replicas", type=int,
                    default=FLAGS.autoscaler_min_replicas)
    ap.add_argument("--max-replicas", type=int,
                    default=FLAGS.autoscaler_max_replicas)
    ap.add_argument("--target-ttft-ms", type=float,
                    default=FLAGS.autoscaler_target_ttft_ms)
    ap.add_argument("--hysteresis", type=float,
                    default=FLAGS.autoscaler_hysteresis)
    ap.add_argument("--poll-interval-s", type=float,
                    default=FLAGS.autoscaler_poll_interval_s)
    ap.add_argument("--cooldown-out-s", type=float,
                    default=FLAGS.autoscaler_cooldown_out_s)
    ap.add_argument("--cooldown-in-s", type=float,
                    default=FLAGS.autoscaler_cooldown_in_s)
    ap.add_argument("--slo-ttft-ms", type=float,
                    default=FLAGS.overload_slo_ttft_ms,
                    help="router brownout-ladder SLO (0 = ladder off); "
                         "independent of the autoscaler target")
    ap.add_argument("--seed", type=int, default=FLAGS.autoscaler_seed)
    ap.add_argument("--smoke", action="store_true",
                    help="autoscale self-test (1 replica + seeded spike "
                         "-> scale-out -> recover -> scale-in, zero "
                         "failed requests), one JSON line, exit")
    args = ap.parse_args(argv)
    if args.smoke:
        return _smoke()

    from paddle_tpu.serving.fleet import ReplicaSupervisor
    from paddle_tpu.serving.router import Router
    sup = ReplicaSupervisor(n_replicas=args.min_replicas,
                            extra_args=args.replica_arg).start()
    router = Router(supervisor=sup, slo_ttft_ms=args.slo_ttft_ms)
    scaler = Autoscaler(sup, router,
                        poll_interval_s=args.poll_interval_s,
                        target_ttft_ms=args.target_ttft_ms,
                        hysteresis=args.hysteresis,
                        cooldown_out_s=args.cooldown_out_s,
                        cooldown_in_s=args.cooldown_in_s,
                        min_replicas=args.min_replicas,
                        max_replicas=args.max_replicas,
                        seed=args.seed).start()
    router.start(args.host, args.port)
    stop = threading.Event()

    def _drain(signum, frame):
        logger.info("SIGTERM: stopping autoscaler + router + fleet")
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _drain)
        signal.signal(signal.SIGINT, _drain)
    except ValueError:
        pass
    try:
        stop.wait()
    finally:
        scaler.close()
        router.close()
        sup.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
