"""Live serving metrics: latency percentiles, queue depth, batch occupancy.

The reference's serving story had no observability beyond host logs; a
dynamic batcher is unoperable without numbers — whether batching is
actually happening (occupancy), how much compute padding burns (waste
ratio), and where the tail latency sits.  One ``ServingMetrics`` instance
is shared by the engine, the batcher, and the HTTP front-end, built on
``utils/stats.py`` (the ``Histogram`` percentile machinery, ``keep="last"``
so a long-running server reports RECENT latency, and a ``global_stats``
timer for the per-batch engine time so ``print_all_stats()`` shows serving
next to training).

``render_prometheus()`` is the text format served at ``/metrics``;
``snapshot()`` is the same data as a dict (the bench family and the smoke
JSON consume it).
"""

import threading

from paddle_tpu.utils.stats import Histogram

# submit() rejection reasons — keys are part of the /metrics surface.
# breaker = the circuit breaker is open (resilience/supervisor.py): the
# engine recently failed M consecutive steps, shed fast with 503.
REJECT_REASONS = ("overload", "deadline", "invalid", "shutdown", "breaker")

# decode-slot eviction reasons (generation serving, decode_engine.py):
# eos = the model emitted the stop token, length = per-request max_tokens
# reached, error = the slot's request failed with its batch, shutdown =
# drain(False) failed it, abandoned = the caller disconnected mid-stream,
# recovered = the slot was torn down by a step failure and re-prefilled
# onto the rebuilt slab (resilience/supervisor.py), pool_exhausted = the
# paged KV block pool ran dry and the slot was preempted (its request
# re-seats and continues bit-identically; serving/kv_pool.py).  Keys are
# part of the /metrics surface.
EVICT_REASONS = ("eos", "length", "error", "shutdown", "abandoned",
                 "recovered", "pool_exhausted")

# cross-replica KV handoff outcomes (serving/transfer.py): sent = this
# replica exported a chain blob to a peer, received = a peer's blob was
# fetched + delivered into the host tier, fallback = the handoff was
# skipped or failed and the stream recomputed its context instead
# (bit-identical either way).  Keys are part of the /metrics surface.
HANDOFF_OUTCOMES = ("sent", "received", "fallback")

# circuit-breaker state gauge encoding (breaker_state metric)
BREAKER_STATES = {"closed": 0, "half_open": 1, "open": 2}

_QUANTILES = (50, 95, 99)


class ServingMetrics:
    """Thread-safe counters + latency/batch histograms for one engine.

    clock: injectable zero-arg monotonic clock threaded into every
    recent-window histogram (default: ``time.monotonic`` — zero behavior
    change), so the autoscaler's windowed SLO reads
    (``ttft.percentiles(window_s=...)``) and the tests that drive them
    run on a simulated clock instead of wall-clock sleeps."""

    def __init__(self, name="paddle_tpu_serving", max_samples=100000,
                 clock=None):
        import time as _time
        self.name = name
        self.clock = clock or _time.monotonic
        self._lock = threading.Lock()
        self.requests_total = 0          # accepted into the queue
        self.responses_total = 0         # futures resolved with a result
        self.errors_total = 0            # futures failed by a batch error
        self.rejected = {r: 0 for r in REJECT_REASONS}
        self.batches_total = 0
        self.batch_rows_total = 0        # real rows executed
        self.batch_slots_total = 0       # padded bucket slots executed
        # request wall latency submit -> future resolved (seconds)
        self.latency = Histogram(f"{name}_latency", max_samples=max_samples,
                                 keep="last", clock=self.clock)
        # engine batch execution time (seconds)
        self.batch_time = Histogram(f"{name}_batch_time",
                                    max_samples=max_samples, keep="last",
                                    clock=self.clock)
        # ---- generation serving (decode_engine.py) ----
        # time-to-first-token: submit -> the request's first token exists
        # (prefill done); the latency a chat user feels before anything
        # streams
        self.ttft = Histogram(f"{name}_ttft", max_samples=max_samples,
                              keep="last", clock=self.clock)
        # time-per-output-token: one slab decode step's wall time — every
        # active request emits exactly one token per step, so this IS the
        # per-token latency of the stream
        self.tpot = Histogram(f"{name}_tpot", max_samples=max_samples,
                              keep="last", clock=self.clock)
        self.gen_tokens_total = 0        # useful (delivered) tokens
        self.decode_steps_total = 0
        self.active_slot_steps_total = 0  # sum of active slots over steps
        self.slot_count = 0              # gauge, set by the decode engine
        # ---- unified chunked prefill (decode_engine.py prefill_chunk):
        # prompt ingestion folded into the decode step as K-lane chunks
        self.prefill_chunks_total = 0    # chunks loaded into steps
        self.prefill_chunk_lanes_total = 0  # teacher-forced lanes loaded
        self.prefill_lane_steps_total = 0   # sum of per-step chunk lanes
        self.prefill_chunk_size = 0      # gauge: engine K (0 = ladder)
        self.evictions = {r: 0 for r in EVICT_REASONS}
        # ---- speculative decoding (serving/speculative.py): draft
        # lanes scored by verify steps and how many the target accepted
        self.speculate_k = 0             # gauge: draft lanes per slot (0=off)
        # ---- tensor-parallel sharded decode (DecodeEngine(mesh=...)):
        # how many chips the ONE jitted step spans (1 = single-chip)
        self.mesh_shards = 1             # gauge: model-axis mesh size
        self.drafted_tokens_total = 0    # draft lanes scored
        self.accepted_tokens_total = 0   # draft lanes accepted (matched)
        self.spec_steps_total = 0        # steps that verified >= 1 span
        self.spec_slot_steps_total = 0   # sum of speculating slots over steps
        # ---- paged KV cache (decode_engine.py kv_layout="paged" over
        # serving/kv_pool.py): block-pool gauges + prefix-sharing and
        # copy-on-write counters
        self.kv_blocks_total = 0         # gauge: allocatable pool blocks
        self.kv_blocks_free = 0          # gauge: free-list depth
        self.kv_dtype = "float32"        # gauge: cache storage dtype
        #                                  ("int8" = quantized serving)
        self.prefix_cache_hits = 0       # fresh admissions seated from
        #                                  resident prefix blocks
        self.prefix_cache_misses = 0     # fresh admissions that prefilled
        self.cow_forks = 0               # copy-on-write block forks
        # ---- hierarchical KV host tier (decode_engine.py kv_host_bytes
        # over serving/kv_pool.HostTier): evicted prefix chains spill to
        # host RAM and restore over the host link instead of recomputing
        self.kv_spill_blocks_total = 0   # blocks serialized to the tier
        self.kv_restore_hits_total = 0   # spilled chains restored + seated
        self.kv_restore_bytes_total = 0  # payload bytes restored H2D
        self.host_tier_bytes = 0         # gauge: resident spilled bytes
        # submit -> commit wall time of one async restore (seconds)
        self.kv_restore = Histogram(f"{name}_kv_restore",
                                    max_samples=max_samples,
                                    keep="last", clock=self.clock)
        # ---- disaggregated serving (serving/transfer.py): KV chains
        # crossing replicas as wire-format blobs at stream handoff
        self.serving_role = "mixed"      # gauge: this replica's fleet role
        self.kv_handoffs = {o: 0 for o in HANDOFF_OUTCOMES}
        self.kv_handoff_bytes_total = 0  # blob bytes sent + received
        # decide -> deliver wall time of one receive-side handoff (s)
        self.kv_handoff = Histogram(f"{name}_kv_handoff",
                                    max_samples=max_samples,
                                    keep="last", clock=self.clock)
        # v2 Inference per-row-signature engine cache (satellite): LRU
        # evictions of whole compiled engines under ragged feed signatures
        self.engine_cache_evictions = 0
        # ---- resilience (resilience/): recovery events all flow here
        self.retries_total = 0           # transient submit retries taken
        self.watchdog_trips_total = 0    # step deadline misses
        self.slot_reprefills_total = 0   # slots rebuilt by re-prefill
        self.breaker_open_total = 0      # times the breaker tripped open
        self.breaker_state = 0           # gauge: 0 closed/1 half-open/2 open
        # wired by batchers: each contributes a zero-arg callable -> its
        # current queue depth; queue_depth() sums them (a combined
        # inference+generation server shares ONE metrics object, and one
        # plane's backlog must never mask another's)
        self.queue_depth_fns = []

    # ------------------------------------------------------------ record

    def accepted(self):
        with self._lock:
            self.requests_total += 1

    def reject(self, reason):
        with self._lock:
            self.rejected[reason] = self.rejected.get(reason, 0) + 1

    def observe_batch(self, n_real, bucket, seconds):
        with self._lock:
            self.batches_total += 1
            self.batch_rows_total += int(n_real)
            self.batch_slots_total += int(bucket)
        self.batch_time.add(seconds)

    def observe_response(self, latency_s):
        with self._lock:
            self.responses_total += 1
        self.latency.add(latency_s)

    def observe_error(self, n=1):
        with self._lock:
            self.errors_total += int(n)

    def observe_ttft(self, seconds):
        self.ttft.add(seconds)

    def observe_decode_step(self, n_active, n_slots, seconds,
                            prefill_lanes=0, accepted_tokens=0,
                            drafted_tokens=0, spec_slots=0):
        """One slab decode step: n_active of n_slots held live requests;
        prefill_lanes = teacher-forced chunk lanes the step fed beyond
        each slot's own token (0 outside chunked-prefill mode).
        Speculative mode adds drafted_tokens (draft lanes the step
        scored), accepted_tokens (lanes the target matched) and
        spec_slots (slots that speculated) — the engine passes these
        kwargs ONLY when a draft trunk is attached, so subclasses with
        the pre-speculation signature keep working unchanged."""
        with self._lock:
            self.decode_steps_total += 1
            self.active_slot_steps_total += int(n_active)
            self.slot_count = int(n_slots)
            self.prefill_lane_steps_total += int(prefill_lanes)
            self.drafted_tokens_total += int(drafted_tokens)
            self.accepted_tokens_total += int(accepted_tokens)
            if spec_slots:
                self.spec_steps_total += 1
                self.spec_slot_steps_total += int(spec_slots)
        self.tpot.add(seconds)

    def observe_prefill_chunk(self, lanes):
        """One prefill chunk loaded into the next step (``lanes``
        teacher-forced lanes beyond the slot's armed token)."""
        with self._lock:
            self.prefill_chunks_total += 1
            self.prefill_chunk_lanes_total += int(lanes)

    def set_prefill_chunk(self, k):
        """Gauge: the engine's chunk size K (0 = legacy ladder mode)."""
        with self._lock:
            self.prefill_chunk_size = int(k)

    def set_speculate_k(self, k):
        """Gauge: the engine's draft lanes per slot (0 = speculation
        off).  Config, like the chunk gauge: the engine's metrics-swap
        setter re-applies it so a fresh object inherits it."""
        with self._lock:
            self.speculate_k = int(k)

    def set_mesh_shards(self, n):
        """Gauge: model-axis mesh size the decode step is sharded over
        (1 = single-chip).  Config, like the chunk/speculate gauges."""
        with self._lock:
            self.mesh_shards = max(1, int(n))

    def observe_gen_tokens(self, n=1):
        with self._lock:
            self.gen_tokens_total += int(n)

    def evict_slot(self, reason):
        with self._lock:
            self.evictions[reason] = self.evictions.get(reason, 0) + 1

    def evict_engine_cache(self):
        with self._lock:
            self.engine_cache_evictions += 1

    # ---- paged KV cache (decode_engine.py / serving/kv_pool.py) ----

    def observe_prefix_cache(self, hit):
        """One fresh admission's prefix-cache outcome: seated from
        resident blocks (hit) or prefilled (miss)."""
        with self._lock:
            if hit:
                self.prefix_cache_hits += 1
            else:
                self.prefix_cache_misses += 1

    def observe_cow_fork(self, n=1):
        with self._lock:
            self.cow_forks += int(n)

    def set_kv_pool(self, free, total):
        """Snapshot the block pool's free/allocatable gauges."""
        with self._lock:
            self.kv_blocks_free = int(free)
            self.kv_blocks_total = int(total)

    def set_kv_dtype(self, kv_dtype):
        """Gauge: the engine's KV-cache storage dtype (quantized
        serving: "int8" -> ``kv_cache_int8 1`` on /metrics)."""
        with self._lock:
            self.kv_dtype = str(kv_dtype)

    def observe_kv_spill(self, blocks):
        """One prefix chain spilled to the host tier at eviction."""
        with self._lock:
            self.kv_spill_blocks_total += int(blocks)

    def observe_kv_restore(self, nbytes, seconds):
        """One spilled chain restored and committed back into the pool
        (``seconds`` = submit -> commit wall time of the async job)."""
        with self._lock:
            self.kv_restore_hits_total += 1
            self.kv_restore_bytes_total += int(nbytes)
        self.kv_restore.add(seconds)

    def set_host_tier_bytes(self, nbytes):
        """Gauge: serialized payload bytes resident in the host tier."""
        with self._lock:
            self.host_tier_bytes = int(nbytes)

    def set_serving_role(self, role):
        """Gauge: this replica's fleet role ("prefill" | "decode" |
        "mixed") — the router reads it off /metrics to build pools."""
        with self._lock:
            self.serving_role = str(role)

    def observe_kv_handoff(self, outcome, nbytes=0, seconds=None):
        """One cross-replica KV handoff event (serving/transfer.py):
        ``outcome`` in ``HANDOFF_OUTCOMES``; ``nbytes`` the blob bytes
        crossing the socket; ``seconds`` the receive side's
        decide-to-deliver wall time."""
        with self._lock:
            self.kv_handoffs[outcome] += 1
            self.kv_handoff_bytes_total += int(nbytes)
        if seconds is not None:
            self.kv_handoff.add(seconds)

    # ---- resilience events (resilience/supervisor.py callers) ----

    def observe_retry(self, n=1):
        with self._lock:
            self.retries_total += int(n)

    def observe_watchdog_trip(self):
        with self._lock:
            self.watchdog_trips_total += 1

    def observe_slot_reprefill(self, n=1):
        with self._lock:
            self.slot_reprefills_total += int(n)

    def set_breaker_state(self, state, opened_total=None):
        """Snapshot the breaker's state ('closed'/'half_open'/'open')
        and cumulative open count into the gauge/counter pair."""
        with self._lock:
            self.breaker_state = BREAKER_STATES.get(state, 0)
            if opened_total is not None:
                self.breaker_open_total = int(opened_total)

    # ------------------------------------------------------------ derive

    @property
    def mean_occupancy(self):
        """Real rows per executed batch (> 1.0 iff batching happened)."""
        with self._lock:
            return (self.batch_rows_total / self.batches_total
                    if self.batches_total else 0.0)

    @property
    def padding_waste(self):
        """Fraction of executed bucket slots that held padding."""
        with self._lock:
            return (1.0 - self.batch_rows_total / self.batch_slots_total
                    if self.batch_slots_total else 0.0)

    @property
    def mean_slot_occupancy(self):
        """Active slots per decode step (generation serving); the fraction
        of the slab doing useful work is this over ``slot_count``."""
        with self._lock:
            return (self.active_slot_steps_total / self.decode_steps_total
                    if self.decode_steps_total else 0.0)

    @property
    def mean_prefill_chunk_occupancy(self):
        """Fraction of the per-step chunk-lane capacity
        (``slots * (K - 1)`` teacher-forced lanes) actually fed, over
        the steps executed — how much of each unified step is prompt
        ingestion vs decode.  0.0 outside chunked mode."""
        with self._lock:
            cap = (self.decode_steps_total * self.slot_count
                   * max(0, self.prefill_chunk_size - 1))
            return (self.prefill_lane_steps_total / cap) if cap else 0.0

    @property
    def spec_acceptance_rate(self):
        """Fraction of drafted lanes the target accepted (speculative
        decoding quality; 0.0 with no drafts scored)."""
        with self._lock:
            return (self.accepted_tokens_total / self.drafted_tokens_total
                    if self.drafted_tokens_total else 0.0)

    @property
    def spec_tokens_per_step(self):
        """Mean emitted tokens per speculating slot-step (each verify
        span emits its accepted run + the target's own token, so this is
        >= 1.0 whenever speculation ran; the headline effective-tokens-
        per-target-step number).  0.0 with no speculation."""
        with self._lock:
            return ((self.accepted_tokens_total + self.spec_slot_steps_total)
                    / self.spec_slot_steps_total
                    if self.spec_slot_steps_total else 0.0)

    def tpot_jitter(self):
        """Recent-window TPOT p99/p50 ratio — the jitter a long-prompt
        admission injects into in-flight streams' token cadence (1.0 =
        perfectly steady; the chunked-prefill acceptance metric).  0.0
        with no samples."""
        pct = self.tpot.percentiles((50, 99))
        p50, p99 = pct.get(50, 0.0), pct.get(99, 0.0)
        return (p99 / p50) if p50 > 0 else 0.0

    def queue_depth(self):
        total = 0
        for fn in list(self.queue_depth_fns):
            try:
                total += int(fn())
            except Exception:   # noqa: BLE001 — a dying queue must not
                pass            # kill /metrics
        return total

    def snapshot(self):
        """All metrics as one dict (bench family / smoke JSON surface)."""
        lat = self.latency.percentiles(_QUANTILES)
        bt = self.batch_time.percentiles(_QUANTILES)
        ttft = self.ttft.percentiles(_QUANTILES)
        tpot = self.tpot.percentiles(_QUANTILES)
        with self._lock:
            out = {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "errors_total": self.errors_total,
                "rejected": dict(self.rejected),
                "batches_total": self.batches_total,
                "batch_rows_total": self.batch_rows_total,
                "batch_slots_total": self.batch_slots_total,
                "gen_tokens_total": self.gen_tokens_total,
                "decode_steps_total": self.decode_steps_total,
                "slot_count": self.slot_count,
                "prefill_chunks_total": self.prefill_chunks_total,
                "prefill_chunk_lanes_total":
                    self.prefill_chunk_lanes_total,
                "prefill_chunk_size": self.prefill_chunk_size,
                "speculate_k": self.speculate_k,
                "mesh_shards": self.mesh_shards,
                "drafted_tokens_total": self.drafted_tokens_total,
                "accepted_tokens_total": self.accepted_tokens_total,
                "spec_steps_total": self.spec_steps_total,
                "spec_slot_steps_total": self.spec_slot_steps_total,
                "evictions": dict(self.evictions),
                "kv_blocks_total": self.kv_blocks_total,
                "kv_blocks_free": self.kv_blocks_free,
                "kv_dtype": self.kv_dtype,
                "kv_blocks_used": self.kv_blocks_total
                - self.kv_blocks_free,
                "kv_block_utilization": round(
                    (self.kv_blocks_total - self.kv_blocks_free)
                    / self.kv_blocks_total, 3) if self.kv_blocks_total
                else 0.0,
                "prefix_cache_hits_total": self.prefix_cache_hits,
                "prefix_cache_misses_total": self.prefix_cache_misses,
                "cow_forks_total": self.cow_forks,
                "kv_spill_blocks_total": self.kv_spill_blocks_total,
                "kv_restore_hits_total": self.kv_restore_hits_total,
                "kv_restore_bytes_total": self.kv_restore_bytes_total,
                "host_tier_bytes": self.host_tier_bytes,
                "serving_role": self.serving_role,
                "kv_handoffs_total": dict(self.kv_handoffs),
                "kv_handoff_bytes_total": self.kv_handoff_bytes_total,
                "engine_cache_evictions": self.engine_cache_evictions,
                "retries_total": self.retries_total,
                "watchdog_trips_total": self.watchdog_trips_total,
                "slot_reprefills_total": self.slot_reprefills_total,
                "breaker_open_total": self.breaker_open_total,
                "breaker_state": self.breaker_state,
            }
        from paddle_tpu.resilience import faults
        out["faults_fired"] = faults.fired_counts()
        out["queue_depth"] = self.queue_depth()
        out["mean_occupancy"] = round(self.mean_occupancy, 3)
        out["padding_waste"] = round(self.padding_waste, 3)
        out["mean_slot_occupancy"] = round(self.mean_slot_occupancy, 3)
        out["mean_prefill_chunk_occupancy"] = round(
            self.mean_prefill_chunk_occupancy, 4)
        out["tpot_jitter_p99_p50"] = round(self.tpot_jitter(), 3)
        out["spec_acceptance_rate"] = round(self.spec_acceptance_rate, 4)
        out["spec_tokens_per_step"] = round(self.spec_tokens_per_step, 4)
        out["latency_ms"] = {f"p{q}": round(v * 1e3, 3)
                             for q, v in lat.items()}
        out["batch_time_ms"] = {f"p{q}": round(v * 1e3, 3)
                                for q, v in bt.items()}
        out["ttft_ms"] = {f"p{q}": round(v * 1e3, 3)
                          for q, v in ttft.items()}
        out["tpot_ms"] = {f"p{q}": round(v * 1e3, 3)
                          for q, v in tpot.items()}
        out["kv_restore_ms"] = {
            f"p{q}": round(v * 1e3, 3)
            for q, v in self.kv_restore.percentiles(_QUANTILES).items()}
        out["kv_handoff_ms"] = {
            f"p{q}": round(v * 1e3, 3)
            for q, v in self.kv_handoff.percentiles(_QUANTILES).items()}
        return out

    # ------------------------------------------------------------ render

    def render_prometheus(self):
        """Prometheus text exposition for the /metrics endpoint."""
        n = self.name
        lat = self.latency.percentiles(_QUANTILES)
        bt = self.batch_time.percentiles(_QUANTILES)
        lines = []

        def emit(metric, value, help_, mtype="gauge", labels=""):
            lines.append(f"# HELP {n}_{metric} {help_}")
            lines.append(f"# TYPE {n}_{metric} {mtype}")
            lines.append(f"{n}_{metric}{labels} {value}")

        with self._lock:
            counters = [
                ("requests_total", self.requests_total,
                 "requests accepted into the batching queue"),
                ("responses_total", self.responses_total,
                 "requests answered with a result"),
                ("errors_total", self.errors_total,
                 "requests failed by a batch execution error"),
                ("batches_total", self.batches_total,
                 "engine batches executed"),
                ("batch_rows_total", self.batch_rows_total,
                 "real request rows executed"),
                ("batch_slots_total", self.batch_slots_total,
                 "bucket slots executed (rows + padding)"),
            ]
            rejected = dict(self.rejected)
        for metric, value, help_ in counters:
            emit(metric, value, help_, mtype="counter")
        lines.append(f"# HELP {n}_rejected_total requests rejected before "
                     "batching, by reason")
        lines.append(f"# TYPE {n}_rejected_total counter")
        for reason in sorted(rejected):
            lines.append(
                f'{n}_rejected_total{{reason="{reason}"}} {rejected[reason]}')
        emit("queue_depth", self.queue_depth(), "requests waiting in queue")
        emit("batch_occupancy_mean", f"{self.mean_occupancy:.6f}",
             "mean real rows per executed batch")
        emit("padding_waste_ratio", f"{self.padding_waste:.6f}",
             "fraction of executed slots that held padding")
        lines.append(f"# HELP {n}_latency_seconds request wall latency "
                     "(submit to response), recent-window quantiles")
        lines.append(f"# TYPE {n}_latency_seconds summary")
        for q, v in lat.items():
            lines.append(
                f'{n}_latency_seconds{{quantile="0.{q}"}} {v:.6f}')
        lines.append(f"{n}_latency_seconds_count {self.latency.count}")
        lines.append(f"# HELP {n}_batch_time_seconds engine batch execution "
                     "time, recent-window quantiles")
        lines.append(f"# TYPE {n}_batch_time_seconds summary")
        for q, v in bt.items():
            lines.append(
                f'{n}_batch_time_seconds{{quantile="0.{q}"}} {v:.6f}')
        lines.append(f"{n}_batch_time_seconds_count {self.batch_time.count}")

        # ---- generation serving (decode_engine.py) ----
        ttft = self.ttft.percentiles(_QUANTILES)
        tpot = self.tpot.percentiles(_QUANTILES)
        with self._lock:
            gen_counters = [
                ("gen_tokens_total", self.gen_tokens_total,
                 "generated tokens delivered to requests"),
                ("decode_steps_total", self.decode_steps_total,
                 "continuous-batching slab decode steps executed"),
                ("engine_cache_evictions_total",
                 self.engine_cache_evictions,
                 "compiled engines evicted from the per-row-signature "
                 "LRU cache"),
                ("prefix_cache_hits_total", self.prefix_cache_hits,
                 "fresh admissions seated from resident prefix blocks "
                 "(paged KV cache)"),
                ("prefix_cache_misses_total", self.prefix_cache_misses,
                 "fresh admissions that re-prefilled (paged KV cache)"),
                ("cow_forks_total", self.cow_forks,
                 "copy-on-write KV block forks (paged KV cache)"),
                ("kv_spill_blocks_total", self.kv_spill_blocks_total,
                 "KV blocks serialized to the host tier at prefix "
                 "eviction (hierarchical KV)"),
                ("kv_restore_hits_total", self.kv_restore_hits_total,
                 "spilled prefix chains restored from the host tier "
                 "and reseated (hierarchical KV)"),
                ("kv_restore_bytes_total", self.kv_restore_bytes_total,
                 "serialized payload bytes restored host-to-device "
                 "(hierarchical KV)"),
                ("prefill_chunks_total", self.prefill_chunks_total,
                 "prompt-ingestion chunks fed through the unified "
                 "decode step (chunked prefill)"),
                ("prefill_chunk_lanes_total",
                 self.prefill_chunk_lanes_total,
                 "teacher-forced chunk lanes fed through the unified "
                 "decode step (chunked prefill)"),
                ("drafted_tokens_total", self.drafted_tokens_total,
                 "draft lanes scored by verify steps (speculative "
                 "decoding)"),
                ("accepted_tokens_total", self.accepted_tokens_total,
                 "draft lanes the target accepted (speculative "
                 "decoding)"),
                ("spec_steps_total", self.spec_steps_total,
                 "decode steps that verified at least one draft span"),
                ("spec_slot_steps_total", self.spec_slot_steps_total,
                 "per-slot verify spans scored (speculating slots "
                 "summed over steps)"),
            ]
            gen_counters.append(
                ("kv_handoff_bytes_total", self.kv_handoff_bytes_total,
                 "KV blob bytes crossing the cross-replica handoff "
                 "socket, sent + received (disaggregated serving)"))
            evictions = dict(self.evictions)
            handoffs = dict(self.kv_handoffs)
            role = self.serving_role
            slot_count = self.slot_count
            kv_total = self.kv_blocks_total
            kv_free = self.kv_blocks_free
            host_bytes = self.host_tier_bytes
            kv_int8 = self.kv_dtype == "int8"
            chunk_size = self.prefill_chunk_size
            spec_k = self.speculate_k
            mesh_shards = self.mesh_shards
        for metric, value, help_ in gen_counters:
            emit(metric, value, help_, mtype="counter")
        emit("prefill_chunk_size", chunk_size,
             "chunked-prefill lanes per step (K; 0 = legacy ladder)")
        emit("prefill_chunk_occupancy_mean",
             f"{self.mean_prefill_chunk_occupancy:.6f}",
             "fraction of per-step chunk-lane capacity fed")
        emit("speculate_k", spec_k,
             "draft lanes per slot per verify step (0 = speculation off)")
        emit("mesh_shards", mesh_shards,
             "model-axis mesh size the decode step spans (1 = "
             "single-chip)")
        emit("spec_acceptance_rate", f"{self.spec_acceptance_rate:.6f}",
             "fraction of drafted lanes the target accepted")
        emit("spec_tokens_per_step", f"{self.spec_tokens_per_step:.6f}",
             "mean emitted tokens per speculating slot-step (>= 1 when "
             "speculation runs)")
        emit("tpot_jitter_p99_p50", f"{self.tpot_jitter():.6f}",
             "recent-window TPOT p99/p50 ratio (token-cadence jitter)")
        emit("kv_blocks_total", kv_total,
             "allocatable KV blocks in the paged pool (0 = slab layout)")
        emit("kv_blocks_free", kv_free, "free KV blocks in the paged pool")
        emit("kv_blocks_used", kv_total - kv_free,
             "KV blocks held by slot chains / the prefix index")
        emit("kv_block_utilization",
             f"{((kv_total - kv_free) / kv_total if kv_total else 0.0):.6f}",
             "fraction of the paged KV pool in use")
        emit("kv_cache_int8", int(kv_int8),
             "1 when the KV cache stores int8 + per-head scale sidecars "
             "(quantized serving; docs/serving.md)")
        emit("host_tier_bytes", host_bytes,
             "serialized KV payload bytes resident in the host spill "
             "tier (hierarchical KV; 0 = tier off)")
        kvr = self.kv_restore.percentiles(_QUANTILES)
        lines.append(f"# HELP {n}_kv_restore_seconds host-tier restore "
                     "submit-to-commit wall time, recent-window quantiles")
        lines.append(f"# TYPE {n}_kv_restore_seconds summary")
        for q, v in kvr.items():
            lines.append(
                f'{n}_kv_restore_seconds{{quantile="0.{q}"}} {v:.6f}')
        lines.append(f"{n}_kv_restore_seconds_count "
                     f"{self.kv_restore.count}")
        emit("serving_role", 1,
             "this replica's disaggregated-fleet role (the router "
             "builds its prefill/decode pools from this)",
             labels=f'{{role="{role}"}}')
        lines.append(f"# HELP {n}_kv_handoffs_total cross-replica KV "
                     "handoffs, by outcome (disaggregated serving)")
        lines.append(f"# TYPE {n}_kv_handoffs_total counter")
        for outcome in sorted(handoffs):
            lines.append(f'{n}_kv_handoffs_total{{outcome="{outcome}"}} '
                         f"{handoffs[outcome]}")
        kvh = self.kv_handoff.percentiles(_QUANTILES)
        lines.append(f"# HELP {n}_kv_handoff_seconds receive-side "
                     "handoff decide-to-deliver wall time, "
                     "recent-window quantiles")
        lines.append(f"# TYPE {n}_kv_handoff_seconds summary")
        for q, v in kvh.items():
            lines.append(
                f'{n}_kv_handoff_seconds{{quantile="0.{q}"}} {v:.6f}')
        lines.append(f"{n}_kv_handoff_seconds_count "
                     f"{self.kv_handoff.count}")
        lines.append(f"# HELP {n}_slot_evictions_total decode slots "
                     "evicted, by reason")
        lines.append(f"# TYPE {n}_slot_evictions_total counter")
        for reason in sorted(evictions):
            lines.append(f'{n}_slot_evictions_total{{reason="{reason}"}} '
                         f"{evictions[reason]}")
        emit("slot_count", slot_count, "decode slots in the slab")
        emit("slot_occupancy_mean", f"{self.mean_slot_occupancy:.6f}",
             "mean active slots per decode step")
        lines.append(f"# HELP {n}_ttft_seconds time to first token "
                     "(submit to first token), recent-window quantiles")
        lines.append(f"# TYPE {n}_ttft_seconds summary")
        for q, v in ttft.items():
            lines.append(f'{n}_ttft_seconds{{quantile="0.{q}"}} {v:.6f}')
        lines.append(f"{n}_ttft_seconds_count {self.ttft.count}")
        lines.append(f"# HELP {n}_tpot_seconds per-output-token latency "
                     "(one slab decode step), recent-window quantiles")
        lines.append(f"# TYPE {n}_tpot_seconds summary")
        for q, v in tpot.items():
            lines.append(f'{n}_tpot_seconds{{quantile="0.{q}"}} {v:.6f}')
        lines.append(f"{n}_tpot_seconds_count {self.tpot.count}")

        # ---- resilience (resilience/: faults, watchdog, breaker) ----
        from paddle_tpu.resilience import faults
        with self._lock:
            res_counters = [
                ("retries_total", self.retries_total,
                 "transient submit failures absorbed by bounded retry"),
                ("watchdog_trips_total", self.watchdog_trips_total,
                 "decode steps abandoned past the watchdog deadline"),
                ("slot_reprefills_total", self.slot_reprefills_total,
                 "decode slots recovered by re-prefill after a rebuild"),
                ("breaker_open_total", self.breaker_open_total,
                 "times the circuit breaker tripped open"),
            ]
            breaker_state = self.breaker_state
        for metric, value, help_ in res_counters:
            emit(metric, value, help_, mtype="counter")
        emit("breaker_state", breaker_state,
             "circuit breaker state (0 closed, 1 half-open, 2 open)")
        fired = faults.fired_counts()
        lines.append(f"# HELP {n}_fault_injections_total injected faults "
                     "fired, by point (resilience/faults.py)")
        lines.append(f"# TYPE {n}_fault_injections_total counter")
        for point in sorted(fired):
            lines.append(f'{n}_fault_injections_total{{point="{point}"}} '
                         f"{fired[point]}")
        return "\n".join(lines) + "\n"
