"""Cross-replica KV-block handoff: the socket transport for
disaggregated prefill/decode serving (docs/serving.md "Disaggregated
serving"; ROADMAP item 2(b)).

PR 18's hierarchical tier made a prefix chain's K/V a RELOCATABLE blob
(``kv_pool.serialize_chain``: versioned, trunk-signed, no block ids) —
"a serialized chain that rides host RAM can ride a socket".  This
module is that socket leg:

* SOURCE side — a prefill replica exposes its resident chains at
  ``POST /v1/kv/export`` (the route lives in serving/server.py; the
  wire helpers live here).  The gather reads the committed cache, which
  belongs to the batcher worker thread, so the HTTP handler queues the
  export and the worker serializes it strictly BETWEEN steps
  (``GenerationBatcher.export_chain``).  The response streams the blob
  behind an 8-byte little-endian length prefix in bounded chunks.

* RECEIVER side — ``receive_chain`` on a decode replica fetches the
  blob, bounds the DECLARED length before any buffer grows to it,
  verifies the envelope (version byte + trunk signature,
  ``kv_pool.peek_chain_header``) and parks it in the engine's host tier
  (``DecodeEngine.deliver_chain_blob``).  The request's ordinary seat
  probe then finds the blob exactly like a locally-spilled chain and
  rides the EXISTING restore pipeline — claim fresh blocks
  all-or-nothing, stage on the ``TransferWorker`` thread overlapped
  with decode steps, commit between steps through the one compiled
  write shape, seat by reference with zero prefill chunk lanes and
  zero new traces.

Every failure — peer dead (the kill -9 case), timeout, oversized or
foreign or garbled blob, analytic model preferring recompute — is a
FALLBACK, never an error: the caller seats through plain
continuation-replay recompute and the greedy stream stays
bit-identical either way.  ``kv_handoffs_total{outcome=
sent|received|fallback}`` on both sides' /metrics prove which path ran.
"""

import http.client
import json
import time
from urllib.parse import urlsplit

from paddle_tpu.obs import trace as obstrace
from paddle_tpu.serving.kv_pool import (MAX_CHAIN_BLOB_BYTES,
                                        WireFormatError,
                                        peek_chain_header)
from paddle_tpu.utils.logging import logger

# the one export route (server.py serves it; fetch_chain calls it)
EXPORT_PATH = "/v1/kv/export"

# streaming granularity for both directions: bounded chunks, so neither
# side ever materializes more than the (already length-bounded) blob
_CHUNK = 1 << 16


class HandoffError(RuntimeError):
    """The socket leg of a KV handoff failed (peer unreachable or dead,
    truncated stream, oversized declared length, non-200 export).
    Always caught by ``receive_chain`` — a handoff failure is a
    recompute fallback, never a client-visible error."""


# --------------------------------------------------------------- wire

def write_blob(wfile, blob):
    """Stream one blob: 8-byte little-endian length prefix, then the
    payload in bounded chunks (the source side of the length-prefixed
    framing ``read_blob`` consumes)."""
    wfile.write(len(blob).to_bytes(8, "little"))
    view = memoryview(blob)
    for off in range(0, len(view), _CHUNK):
        wfile.write(view[off:off + _CHUNK])


def read_blob(rfile, max_bytes=MAX_CHAIN_BLOB_BYTES):
    """Read one length-prefixed blob from a stream.  The DECLARED
    length is checked against ``max_bytes`` before the receive buffer
    grows toward it, and the actual stream must deliver exactly that
    many bytes — a malicious or garbled peer can neither OOM the
    receiver nor smuggle trailing bytes."""
    prefix = rfile.read(8)
    if len(prefix) != 8:
        raise HandoffError(
            f"handoff stream ended inside the length prefix "
            f"({len(prefix)} byte(s))")
    n = int.from_bytes(prefix, "little")
    if n > int(max_bytes):
        raise HandoffError(
            f"handoff blob declares {n} byte(s), over the "
            f"{int(max_bytes)}-byte receive bound")
    buf = bytearray()
    while len(buf) < n:
        chunk = rfile.read(min(_CHUNK, n - len(buf)))
        if not chunk:
            raise HandoffError(
                f"handoff stream truncated at {len(buf)}/{n} byte(s)")
        buf += chunk
    return bytes(buf)


def fetch_chain(source, tokens, trunk_sig, max_bytes=MAX_CHAIN_BLOB_BYTES,
                timeout=5.0):
    """Fetch the longest exported coverage of ``tokens`` from a peer
    replica's ``/v1/kv/export``.  Returns ``(covered, blob)`` with the
    blob's envelope already verified against ``trunk_sig`` (version
    byte, header, signature, size bound) — the payload itself is
    validated again by ``restore_chain`` when the restore stages.

    Raises ``HandoffError`` on any socket/HTTP failure and
    ``WireFormatError``/``WireVersionError`` on a foreign or garbled
    blob."""
    u = urlsplit(source)
    body = json.dumps({"tokens": [int(t) for t in tokens]},
                      sort_keys=True).encode("utf-8")
    conn = http.client.HTTPConnection(u.hostname, u.port or 80,
                                      timeout=timeout)
    try:
        try:
            conn.request("POST", EXPORT_PATH, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                detail = resp.read(256)
                raise HandoffError(
                    f"export from {source} failed: HTTP {resp.status} "
                    f"{detail[:120]!r}")
            blob = read_blob(resp, max_bytes=max_bytes)
        except (OSError, http.client.HTTPException) as e:
            raise HandoffError(f"export from {source} failed: "
                               f"{type(e).__name__}: {e}") from e
    finally:
        conn.close()
    header = peek_chain_header(blob, trunk_sig, max_bytes)
    return int(header["covered"]), blob


# ----------------------------------------------------------- receiver

def receive_chain(engine, source, tokens, metrics=None,
                  max_bytes=MAX_CHAIN_BLOB_BYTES, timeout=5.0):
    """The decode-replica receive path: decide (analytic model), fetch
    (socket), verify (envelope) and deliver (host tier) one handed-off
    chain, so the request that follows seats it by reference through
    the existing restore pipeline.

    NEVER raises — every failure mode IS the fallback (the caller
    submits the request unchanged and continuation-replay recomputes
    the context, bit-identically).  Returns an outcome dict:
    ``{"outcome": "received"|"fallback", "bytes", "covered",
    "ms", "reason"}``; counters/histograms land on ``metrics``
    (``ServingMetrics.observe_kv_handoff``) when given."""
    t0 = time.perf_counter()

    def _fallback(reason):
        if metrics is not None:
            metrics.observe_kv_handoff("fallback")
        obstrace.instant("kv.handoff_fallback", reason=reason,
                         source=str(source))
        return {"outcome": "fallback", "bytes": 0, "covered": 0,
                "ms": round((time.perf_counter() - t0) * 1e3, 3),
                "reason": reason}

    if engine.host_tier is None:
        return _fallback("no_host_tier")
    toks = [int(t) for t in tokens]
    est = (len(toks) // engine.block_size) * engine.block_size
    if est <= 0:
        return _fallback("below_block")
    key, covered, _blob = engine.host_tier.lookup(toks, engine.block_size)
    if key is not None:
        # an earlier handoff (e.g. a failover retry) already delivered
        # this coverage — nothing to fetch, the seat probe will hit it
        return {"outcome": "received", "bytes": 0, "covered": covered,
                "ms": round((time.perf_counter() - t0) * 1e3, 3),
                "reason": "resident"}
    faster, handoff_ms, recompute_ms = \
        engine._handoff_predicted_faster(est)
    obstrace.instant("kv.handoff_route", covered=int(est),
                     handoff_ms=round(handoff_ms, 4),
                     recompute_ms=round(recompute_ms, 4),
                     handoff=faster)
    if not faster:
        return _fallback("analytic")
    try:
        covered, blob = fetch_chain(source, toks, engine._trunk_sig,
                                    max_bytes=max_bytes, timeout=timeout)
        key, covered = engine.deliver_chain_blob(blob,
                                                 max_bytes=max_bytes)
    except (HandoffError, WireFormatError, ValueError) as e:
        logger.warning("kv handoff from %s fell back to recompute: "
                       "%s: %s", source, type(e).__name__, e)
        return _fallback(type(e).__name__)
    dt = time.perf_counter() - t0
    if metrics is not None:
        metrics.observe_kv_handoff("received", len(blob), dt)
    obstrace.instant("kv.handoff_recv", bytes=len(blob),
                     covered=int(covered), source=str(source),
                     ms=round(dt * 1e3, 3))
    return {"outcome": "received", "bytes": len(blob),
            "covered": int(covered), "ms": round(dt * 1e3, 3),
            "reason": None}
