"""Health-checked request router over serving replicas
(docs/serving.md §7).

The fleet supervisor (serving/fleet.py) keeps N replica processes
alive; this module is the front door that keeps one sick replica from
ever owning a user's tail latency or killing their stream ("The Tail at
Scale" playbook over PR-6's resilience substrate):

* READINESS-GATED ADMISSION — a background poller probes every
  replica's ``/readyz`` (honoring its ``Retry-After``) and ``/metrics``
  queue depth; dispatch only considers replicas whose last probe said
  ready.  A draining replica (rolling restart) or one with an open
  in-process breaker drops out of rotation the moment it says so.
  When NOTHING looks eligible, dispatch probes the unready replicas
  itself and waits up to ``router_unready_grace_s`` before failing the
  request — the poller's view of a freshly restarted replica lags by up
  to a poll interval, exactly the rolling-restart window.
* LEAST-LOADED DISPATCH — among eligible replicas, pick the smallest
  (polled queue depth + router-side in-flight count).
* OUTLIER EJECTION — per-replica ``CircuitBreaker`` (the PR-6 class):
  ``router_eject_threshold`` CONSECUTIVE dispatch failures eject the
  replica from rotation; after ``router_eject_cooldown_s`` one
  half-open probe request readmits it on success.
* BOUNDED RETRY — ``/v1/infer`` is idempotent: a transport failure
  retries on a different replica up to ``router_retry_budget`` times.
* HEDGED REQUESTS (optional, ``router_hedge_ms``) — when the primary
  has not answered within the hedge delay (fixed, or p99-derived from
  the router's own recent latency when negative), the same infer fires
  on a second replica and the first answer wins.
* CROSS-REPLICA MID-STREAM FAILOVER — the headline guarantee: when a
  replica dies (kill -9) or is ejected mid-``/v1/generate`` stream, the
  router re-submits ``prompt`` + the tokens already delivered as a
  CONTINUATION (``"replay"``, decode_engine.py) to a healthy replica
  and keeps streaming.  Greedy decode is deterministic, so the client's
  stream finishes BIT-IDENTICAL to an uninterrupted ``lm_generate`` —
  PR-6's in-process slot recovery generalized across process
  boundaries.  Session affinity (``"session"`` in the body) pins a
  conversation to one replica until failover re-pins it.
* CLIENT-DISCONNECT PROPAGATION — a dropped downstream stream closes
  the upstream replica connection, so the replica's ``abandon()`` slot
  reclamation fires instead of decoding to max_tokens for nobody.
* DISAGGREGATED PREFILL/DECODE (serving/transfer.py; docs/serving.md
  "Disaggregated serving") — when the ready set holds both a
  prefill-role and a decode-role replica (``--role`` on server.py,
  advertised via /metrics), a fresh stream runs a 1-token PREFILL leg
  on the prefill pool, then hands off at the first token: the decode
  leg carries chain key + continuation and the decode replica pulls the
  KV blocks over ``/v1/kv/export`` (length-prefixed, trunk-signed spill
  blobs).  Every failure — dead prefill (kill -9), oversized/foreign
  blob, the analytic model preferring recompute — degrades to the plain
  continuation-replay leg, bit-identical by greedy determinism;
  ``kv_handoffs_total{outcome=...}`` counters on the replicas and the
  router prove which path ran.

The ``router.dispatch`` fault point (resilience/faults.py) sits at the
router->replica network boundary: seeded plans inject dispatch errors/
hangs that replay bit-for-bit, like the in-process seven.

CLI (``python -m paddle_tpu.serving.router``):
  --replicas N --replica-arg ...   spawn a managed fleet (fleet.py)
  --backends URL,URL               route over externally-managed replicas
  --smoke                          self-test: 2 tiny replicas, concurrent
                                   generate, kill -9 one mid-stream,
                                   assert bit-identical completion +
                                   /metrics evidence; ONE JSON line
                                   (healthy_window.sh phase 10)
  --smoke-disagg                   disaggregated-serving self-test:
                                   1 prefill + 1 decode replica,
                                   concurrent streams handed off at the
                                   first token over the socket KV
                                   transport, analytic fallback for a
                                   short prompt, kill -9 of the prefill
                                   replica falls back to recompute —
                                   every stream bit-identical; ONE JSON
                                   line (healthy_window.sh phase 21)
"""

import argparse
import http.client
import json
import queue as _queue
import re
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit

from paddle_tpu.obs import trace as obstrace
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.supervisor import CircuitBreaker
from paddle_tpu.utils.logging import log_context, logger
from paddle_tpu.utils.stats import Histogram

_QUANTILES = (50, 95, 99)
_QDEPTH_RE = re.compile(r"^\S*_queue_depth (\d+)\s*$", re.MULTILINE)
# disaggregated serving (serving/transfer.py): each replica advertises
# its role on /metrics; the router parses it from the SAME text the
# queue-depth probe already fetched (zero extra requests)
_ROLE_RE = re.compile(r'^\S*_serving_role\{role="(\w+)"\} 1\s*$',
                      re.MULTILINE)

# router-side rejection reasons (part of the /metrics surface);
# shed = the adaptive overload controller refused it (serving/overload.py)
ROUTER_REJECT_REASONS = ("unready", "exhausted", "shed")


class RouterMetrics:
    """Thread-safe router-side counters + latency/TTFT histograms.
    Replica gauges (ready/queue depth/breaker state) are rendered live
    by the Router from its replica views.

    clock: injectable zero-arg monotonic clock threaded into the
    recent-window histograms (default real clock, zero behavior change)
    so the autoscaler's windowed SLO reads — ``slo_p99_recent_s`` — are
    deterministically testable on a simulated clock."""

    def __init__(self, name="paddle_tpu_router", max_samples=100000,
                 clock=None):
        self.name = name
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self.requests_total = {"infer": 0, "generate": 0}
        self.responses_total = 0
        self.rejected = {r: 0 for r in ROUTER_REJECT_REASONS}
        self.dispatch_total = {}          # replica id -> attempts
        self.dispatch_errors_total = {}   # replica id -> transport/5xx
        self.retries_total = 0            # idempotent infer re-dispatches
        self.failovers_total = 0          # generate re-dispatches (any)
        self.midstream_failovers_total = 0  # ... with tokens already out
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self.ejections_total = {}         # replica id -> breaker opens
        self.readmissions_total = {}      # replica id -> half-open closes
        self.client_disconnects_total = 0
        self.tokens_proxied_total = 0
        # disaggregated prefill/decode handoffs as the ROUTER saw them
        # resolve (the replicas keep their own sent/received/fallback
        # counters; serving/transfer.py)
        from paddle_tpu.serving.metrics import HANDOFF_OUTCOMES
        self.kv_handoffs = {o: 0 for o in HANDOFF_OUTCOMES}
        self.kv_handoff_bytes_total = 0
        self.kv_handoff = Histogram(f"{name}_kv_handoff",
                                    max_samples=max_samples,
                                    keep="last", clock=self.clock)
        self.latency = Histogram(f"{name}_latency", max_samples=max_samples,
                                 keep="last", clock=self.clock)
        # fleet-wide time-to-first-token as the ROUTER's clients feel it
        # (streaming: first forwarded token; unary generate: the
        # replica-reported ttft_ms) — the autoscaler's primary SLO signal
        self.ttft = Histogram(f"{name}_ttft", max_samples=max_samples,
                              keep="last", clock=self.clock)

    def observe_ttft(self, seconds):
        self.ttft.add(seconds)

    def observe_kv_handoff(self, outcome, nbytes=0, seconds=None):
        """One disaggregated KV handoff resolved through this router
        (outcome from serving.metrics.HANDOFF_OUTCOMES; seconds = the
        receive-side fetch+verify+deliver latency when known)."""
        with self._lock:
            self.kv_handoffs[outcome] = \
                self.kv_handoffs.get(outcome, 0) + 1
            self.kv_handoff_bytes_total += int(nbytes)
        if seconds is not None:
            self.kv_handoff.add(seconds)

    def slo_p99_recent_s(self, window_s=None):
        """The control loops' SLO signal: recent-window TTFT p99, falling
        back to request-latency p99 when no generation traffic has
        produced TTFT samples (an infer-only fleet still gets latency-
        based control).  Returns None when NEITHER histogram holds a
        sample in the window — during a total stall nothing completes,
        and an absent signal must never read as 'healthy 0ms' (the
        brownout ladder holds its rung; the autoscaler treats no-signal
        as slack only when the fleet is provably idle)."""
        import numpy as np
        for hist in (self.ttft, self.latency):
            # ONE filtered read per histogram: checking emptiness and
            # computing the percentile from the same snapshot (two
            # separate windowed calls could race the window edge and
            # fabricate a healthy 0.0)
            samples = hist.recent_samples(window_s)
            if samples:
                return float(np.percentile(np.asarray(samples), 99))
        return None

    def _bump(self, table, rid, n=1):
        with self._lock:
            table[rid] = table.get(rid, 0) + n

    def accepted(self, route):
        with self._lock:
            self.requests_total[route] = \
                self.requests_total.get(route, 0) + 1

    def reject(self, reason):
        with self._lock:
            self.rejected[reason] = self.rejected.get(reason, 0) + 1

    def observe_response(self, latency_s):
        with self._lock:
            self.responses_total += 1
        self.latency.add(latency_s)

    def count(self, field, n=1):
        with self._lock:
            setattr(self, field, getattr(self, field) + int(n))

    def snapshot(self):
        lat = self.latency.percentiles(_QUANTILES)
        with self._lock:
            out = {
                "requests_total": dict(self.requests_total),
                "responses_total": self.responses_total,
                "rejected": dict(self.rejected),
                "dispatch_total": dict(self.dispatch_total),
                "dispatch_errors_total": dict(self.dispatch_errors_total),
                "retries_total": self.retries_total,
                "failovers_total": self.failovers_total,
                "midstream_failovers_total": self.midstream_failovers_total,
                "hedges_total": self.hedges_total,
                "hedge_wins_total": self.hedge_wins_total,
                "ejections_total": dict(self.ejections_total),
                "readmissions_total": dict(self.readmissions_total),
                "client_disconnects_total": self.client_disconnects_total,
                "tokens_proxied_total": self.tokens_proxied_total,
                "kv_handoffs_total": dict(self.kv_handoffs),
                "kv_handoff_bytes_total": self.kv_handoff_bytes_total,
            }
        out["faults_fired"] = faults.fired_counts()
        out["kv_handoff_ms"] = {f"p{q}": round(v * 1e3, 3)
                                for q, v in self.kv_handoff.percentiles(
                                    _QUANTILES).items()}
        out["latency_ms"] = {f"p{q}": round(v * 1e3, 3)
                             for q, v in lat.items()}
        out["ttft_ms"] = {f"p{q}": round(v * 1e3, 3)
                          for q, v in self.ttft.percentiles(
                              _QUANTILES).items()}
        return out


class _ReplicaView:
    """The router's live view of one replica: last-polled readiness +
    load, and its outlier-ejection breaker.  A replica that restarts at
    a new URL gets a FRESH view (fresh breaker — a new process has no
    failure history)."""

    def __init__(self, rid, base_url, eject_threshold, eject_cooldown_s,
                 clock=None):
        self.rid = rid
        self.base_url = base_url.rstrip("/")
        u = urlsplit(self.base_url)
        self.host, self.port = u.hostname, u.port
        self.breaker = CircuitBreaker(eject_threshold, eject_cooldown_s,
                                      clock=clock)
        self.ready = False
        self.not_before = 0.0         # honored Retry-After (monotonic)
        self.queue_depth = 0
        self.inflight = 0
        self.role = "mixed"           # serving_role{role=...} from the
        #                               probe's /metrics read (prefill|
        #                               decode|mixed; transfer.py)


class Router:
    """Dispatch ``/v1/infer`` and ``/v1/generate`` across replicas.

    replicas: static list of base URLs, OR supervisor: a
    ``ReplicaSupervisor`` whose ``endpoints()`` is re-read every poll
    (restarted replicas appear at their new ports automatically).
    Tuning knobs default from utils/flags.py (``router_*``).
    """

    def __init__(self, replicas=None, supervisor=None,
                 poll_interval_s=None, unready_grace_s=None,
                 eject_threshold=None,
                 eject_cooldown_s=None, retry_budget=None, hedge_ms=None,
                 request_timeout_s=300.0, name="router", metrics=None,
                 overload=None, slo_ttft_ms=None, slo_window_s=None,
                 clock=None):
        from paddle_tpu.serving.overload import (AIMDLimiter,
                                                 BrownoutLadder,
                                                 OverloadController)
        from paddle_tpu.utils.flags import FLAGS
        if (replicas is None) == (supervisor is None):
            raise ValueError("Router needs exactly one of replicas= "
                             "(static URLs) or supervisor= (managed "
                             "fleet)")
        self.supervisor = supervisor
        # injectable monotonic clock: every time comparison the router
        # makes (Retry-After penalties, grace deadlines, SLO windows)
        # reads it, so tests drive the control surfaces on a simulated
        # clock instead of wall-clock sleeps (default: time.monotonic)
        self._clock = clock or time.monotonic
        self.poll_interval_s = float(
            poll_interval_s if poll_interval_s is not None
            else FLAGS.router_poll_interval_s)
        self.unready_grace_s = float(
            unready_grace_s if unready_grace_s is not None
            else FLAGS.router_unready_grace_s)
        self.eject_threshold = int(
            eject_threshold if eject_threshold is not None
            else FLAGS.router_eject_threshold)
        self.eject_cooldown_s = float(
            eject_cooldown_s if eject_cooldown_s is not None
            else FLAGS.router_eject_cooldown_s)
        self.retry_budget = int(retry_budget if retry_budget is not None
                                else FLAGS.router_retry_budget)
        self.hedge_ms = float(hedge_ms if hedge_ms is not None
                              else FLAGS.router_hedge_ms)
        self.request_timeout_s = float(request_timeout_s)
        self.name = name
        self.metrics = metrics or RouterMetrics(clock=self._clock)
        # adaptive overload control (serving/overload.py): AIMD
        # concurrency limit + priority shedding ahead of dispatch, and
        # the brownout ladder driven by the poll loop's SLO reads.  The
        # default ladder is DISABLED (overload_slo_ttft_ms = 0) and the
        # default limiter bounds sit far above normal load, so a router
        # constructed without arguments behaves exactly as before.
        self.slo_ttft_ms = float(slo_ttft_ms if slo_ttft_ms is not None
                                 else FLAGS.overload_slo_ttft_ms)
        self.slo_window_s = float(slo_window_s if slo_window_s is not None
                                  else FLAGS.overload_window_s)
        self.overload = overload or OverloadController(
            limiter=AIMDLimiter(
                initial=FLAGS.overload_limit_initial,
                min_limit=FLAGS.overload_limit_min,
                max_limit=FLAGS.overload_limit_max,
                increase=FLAGS.overload_aimd_increase,
                decrease=FLAGS.overload_aimd_decrease,
                clock=self._clock),
            ladder=BrownoutLadder(
                slo_ttft_s=self.slo_ttft_ms / 1e3,
                enter_hold_s=FLAGS.overload_brownout_hold_s,
                exit_hold_s=FLAGS.overload_brownout_exit_s,
                clock=self._clock),
            drain_window_s=self.slo_window_s,
            brownout_max_tokens=FLAGS.overload_brownout_max_tokens,
            clock=self._clock)
        # extra /metrics contributors (the autoscaler appends its
        # autoscaler_* lines here); each is a zero-arg -> [str]
        self.extra_render_fns = [
            lambda: self.overload.render_lines(self.metrics.name)]
        self._lock = threading.Lock()
        self._replicas = {}
        self._affinity = {}           # session key -> replica id
        self._breaker_state = {}      # replica id -> last seen state
        self._breaker_lock = threading.Lock()   # keeps the transition
        #                                         counters exact under
        #                                         poll/dispatch races
        if replicas is not None:
            for i, url in enumerate(replicas):
                self._replicas[f"r{i}"] = _ReplicaView(
                    f"r{i}", url, self.eject_threshold,
                    self.eject_cooldown_s, clock=self._clock)
        self._closed = threading.Event()
        self._httpd = None
        self._poller = threading.Thread(target=self._poll_loop, daemon=True,
                                        name=f"{self.name}-health")
        self._poller.start()

    # ------------------------------------------------------------ health

    def _sync_replicas(self):
        if self.supervisor is None:
            return
        eps = dict(self.supervisor.endpoints())
        with self._lock:
            for rid, url in eps.items():
                cur = self._replicas.get(rid)
                if cur is None or cur.base_url != url.rstrip("/"):
                    # new or restarted-at-a-new-port replica: fresh view
                    self._replicas[rid] = _ReplicaView(
                        rid, url, self.eject_threshold,
                        self.eject_cooldown_s, clock=self._clock)
            for rid in [r for r in self._replicas if r not in eps]:
                del self._replicas[rid]

    def _probe(self, rep):
        """One readiness + load probe of one replica (poll thread)."""
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(f"{rep.base_url}/readyz",
                                        timeout=5) as r:
                rep.ready = r.status == 200
            # a live 200 clears any stale Retry-After penalty (e.g. a
            # drain's long hint when the port got reused by the restart)
            rep.not_before = 0.0
        except urllib.error.HTTPError as e:
            rep.ready = False
            ra = e.headers.get("Retry-After")
            if ra is not None:
                try:
                    rep.not_before = self._clock() + float(ra)
                except ValueError:
                    pass
            e.close()
            return
        except Exception:   # noqa: BLE001 — unreachable counts unready
            rep.ready = False
            return
        try:
            with urllib.request.urlopen(f"{rep.base_url}/metrics",
                                        timeout=5) as r:
                text = r.read().decode()
            m = _QDEPTH_RE.search(text)
            if m is not None:
                rep.queue_depth = int(m.group(1))
            m = _ROLE_RE.search(text)
            if m is not None:
                rep.role = m.group(1)
        except Exception:   # noqa: BLE001 — depth/role are advisory
            pass

    def _poll_loop(self):
        while not self._closed.is_set():
            self._sync_replicas()
            with self._lock:
                reps = list(self._replicas.values())
            for rep in reps:
                self._probe(rep)
            self._track_breakers()
            # one SLO evaluation per poll: the brownout ladder sees the
            # recent-window TTFT p99 on the same cadence the replicas
            # are probed.  Gated on the ROUTER's slo_ttft_ms (not just
            # the ladder's) so tests can drive an enabled ladder by hand
            # on a simulated clock without the poll thread racing it.
            if self.slo_ttft_ms > 0 and self.overload.ladder.enabled:
                p99 = self.metrics.slo_p99_recent_s(self.slo_window_s)
                # an empty window (total stall: nothing completed) is NOT
                # health — hold the current rung rather than walk down
                if p99 is not None:
                    rung = self.overload.observe_slo(p99)
                    if rung != getattr(self, "_last_rung", 0):
                        obstrace.instant("router.brownout", rung=rung)
                        logger.warning("%s: brownout rung -> %d",
                                       self.name, rung)
                        self._last_rung = rung
            self._closed.wait(self.poll_interval_s)

    def _track_breakers(self):
        """Count breaker-state TRANSITIONS into ejection/readmission
        counters (the breaker itself only exposes state).  Serialized:
        a poll-thread/dispatch-thread race must not double-count a
        transition."""
        with self._lock:
            reps = list(self._replicas.values())
        with self._breaker_lock:
            self._track_breakers_locked(reps)

    def _track_breakers_locked(self, reps):
        for rep in reps:
            st = rep.breaker.state
            prev = self._breaker_state.get(rep.rid)
            if st == "open" and prev in (None, "closed", "half_open"):
                self.metrics._bump(self.metrics.ejections_total, rep.rid)
                obstrace.instant("router.ejected", replica=rep.rid)
                logger.warning("%s: replica %s EJECTED (%d consecutive "
                               "dispatch failures); half-open probe in "
                               "%.1fs", self.name, rep.rid,
                               rep.breaker.threshold,
                               rep.breaker.cooldown_s)
            elif st == "closed" and prev in ("open", "half_open"):
                self.metrics._bump(self.metrics.readmissions_total,
                                   rep.rid)
                obstrace.instant("router.readmitted", replica=rep.rid)
                logger.info("%s: replica %s readmitted (probe succeeded)",
                            self.name, rep.rid)
            self._breaker_state[rep.rid] = st

    # ------------------------------------------------------------ picking

    @staticmethod
    def _role_penalty(rep, prefer_role):
        """0 = the preferred role, 1 = a mixed replica (serves both
        phases), 2 = the opposite role — a dead prefill pool degrades to
        ANY replica rather than failing the request."""
        if prefer_role is None or rep.role == prefer_role:
            return 0
        return 1 if rep.role == "mixed" else 2

    def _pick(self, exclude=(), session=None, prefer_role=None):
        """Least-loaded eligible replica, or None.  ``session`` pins a
        conversation to its previous replica while that replica stays
        eligible (re-pinned on failover).  ``prefer_role`` biases toward
        a disaggregated-serving role (prefill for new prompts, decode
        for handed-off streams) WITHOUT excluding anyone — the role sort
        key outranks load, and session affinity outranks both."""
        now = self._clock()
        with self._lock:
            cands = sorted(
                (r for r in self._replicas.values()
                 if r.rid not in exclude and r.ready
                 and now >= r.not_before),
                key=lambda r: (self._role_penalty(r, prefer_role),
                               r.queue_depth + r.inflight, r.rid))
            if session is not None:
                pinned = self._affinity.get(session)
                cands.sort(key=lambda r: 0 if r.rid == pinned else 1)
        for r in cands:
            ok, _ = r.breaker.admit()
            if ok:
                if session is not None:
                    with self._lock:
                        if len(self._affinity) > 100000:
                            self._affinity.clear()    # bounded memory
                        self._affinity[session] = r.rid
                return r
        return None

    def _pick_eligible(self, exclude=(), session=None, prefer_role=None):
        """``_pick`` plus the retry-anywhere fallback: when nothing ELSE
        is eligible, a transient blip is still retryable on a replica
        that already failed this request."""
        rep = self._pick(exclude=exclude, session=session,
                         prefer_role=prefer_role)
        if rep is None and exclude:
            rep = self._pick(session=session, prefer_role=prefer_role)
        return rep

    def _pick_wait(self, exclude=(), session=None, prefer_role=None):
        """``_pick_eligible``, but a miss does not immediately fail the
        request: the poll thread's view of a freshly restarted replica
        lags by up to a full interval (exactly the rolling-restart
        window where the NEXT victim goes down while the previous one
        is back but not yet re-probed), so probe the unready replicas
        synchronously and wait the transient out, bounded by
        ``unready_grace_s``."""
        rep = self._pick_eligible(exclude, session, prefer_role)
        if rep is not None:
            return rep
        deadline = self._clock() + self.unready_grace_s
        while not self._closed.is_set():
            self._sync_replicas()     # a restarted replica may have just
            #                           appeared at a new port
            with self._lock:
                stale = [r for r in self._replicas.values() if not r.ready]
            for r in stale:
                self._probe(r)
            if stale:
                self._track_breakers()
            rep = self._pick_eligible(exclude, session, prefer_role)
            if rep is not None or self._clock() >= deadline:
                return rep
            self._closed.wait(0.05)
        return None

    def disagg_active(self):
        """True when disaggregated prefill/decode orchestration should
        run: handoffs are enabled AND the ready set contains both a
        prefill-role and a decode-role replica.  An all-mixed fleet (the
        default) never pays the extra leg; a half-dead disagg fleet
        degrades to ordinary routing."""
        from paddle_tpu.utils.flags import FLAGS
        if not FLAGS.serving_handoff:
            return False
        with self._lock:
            roles = {r.role for r in self._replicas.values() if r.ready}
        return "prefill" in roles and "decode" in roles

    def _retry_after_hint(self):
        """Seconds until routing could plausibly succeed — min over
        replicas of (Retry-After remaining, breaker probe delay, one
        poll interval)."""
        now = self._clock()
        with self._lock:
            reps = list(self._replicas.values())
        if not reps:
            return max(1, int(round(self.poll_interval_s + 0.5)))
        hints = []
        for r in reps:
            h = self.poll_interval_s
            if not r.ready:
                h = max(h, r.not_before - now)
            h = max(h, r.breaker.seconds_until_probe())
            hints.append(h)
        return max(1, int(round(min(hints) + 0.5)))

    # ------------------------------------------------------------ dispatch

    def _dispatch(self, rep, method, path, body=None, timeout=None,
                  stream=False, ctx=None):
        """One upstream exchange against one replica.  The fault point
        sits HERE — the router->replica network boundary: an injected
        error models a failed dispatch, an injected hang a stalled one
        (both drive the same retry/failover paths a real network fault
        would).  stream=True returns (conn, resp) with the connection
        left open; the caller owns closing it.

        Tracing (obs/trace.py): each dispatch is a span (child of the
        router's request root — or of ``ctx``, for hedge threads that
        lose the ambient context), and its span id rides to the replica
        in a ``traceparent`` header, so the replica's ``server.request``
        span parents HERE and one trace_id stitches the whole hop."""
        self.metrics._bump(self.metrics.dispatch_total, rep.rid)
        faults.hit("router.dispatch")
        sp = obstrace.start_span("router.dispatch", ctx=ctx,
                                 replica=rep.rid, path=path)
        conn = http.client.HTTPConnection(
            rep.host, rep.port,
            timeout=timeout if timeout is not None
            else self.request_timeout_s)
        try:
            headers = {"Content-Type": "application/json"} if body else {}
            if sp.trace_id:
                obstrace.inject(headers, ctx=(sp.trace_id, sp.span_id))
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
        except Exception as e:
            sp.end(error=f"{type(e).__name__}: {e}")
            conn.close()
            raise
        sp.end(status=resp.status)
        if stream:
            return conn, resp
        try:
            data = resp.read()
        finally:
            conn.close()
        return resp.status, dict(resp.getheaders()), data

    def _record(self, rep, ok):
        """Per-replica outlier accounting: transport failures (and 5xx
        other than an orderly 503) count toward ejection; any orderly
        answer counts as health."""
        if ok:
            rep.breaker.record_success()
        else:
            self.metrics._bump(self.metrics.dispatch_errors_total, rep.rid)
            rep.breaker.record_failure()
        self._track_breakers()

    def _honor_503(self, rep, headers):
        """An orderly 503 (draining / breaker / warming): take the
        replica out of rotation for its advertised Retry-After without
        charging its ejection breaker."""
        rep.ready = False
        ra = (headers or {}).get("Retry-After")
        try:
            rep.not_before = self._clock() + float(ra)
        except (TypeError, ValueError):
            rep.not_before = self._clock() + self.poll_interval_s
        rep.breaker.release_probe()

    # ------------------------------------------------------------ unary

    def _call(self, rep, path, body, ctx=None):
        """One accounted unary dispatch: returns (status, headers, data);
        raises on transport failure (breaker charged)."""
        with self._lock:
            rep.inflight += 1
        try:
            st, hd, data = self._dispatch(rep, "POST", path, body, ctx=ctx)
        except Exception:
            self._record(rep, ok=False)
            raise
        finally:
            with self._lock:
                rep.inflight -= 1
        self._record(rep, ok=st < 500 or st == 503)
        return st, hd, data

    def _hedge_delay_s(self):
        if self.hedge_ms == 0:
            return None
        if self.hedge_ms > 0:
            return self.hedge_ms / 1e3
        p99 = self.latency_p99_s()
        return p99 if p99 > 0 else 0.05

    def latency_p99_s(self):
        return self.metrics.latency.percentiles((99,)).get(99, 0.0)

    def _hedged_call(self, rep, path, body, exclude):
        """Primary dispatch with an optional hedge: if the primary has
        not answered within the hedge delay, the same (idempotent)
        request fires on a second replica and the first answer wins —
        the tied-request tail-taming move."""
        delay = self._hedge_delay_s()
        if delay is None:
            return self._call(rep, path, body)
        results = _queue.Queue()
        # hedge legs run on fresh threads, which do NOT inherit the
        # handler's context-local span — hand them the parent explicitly
        ctx = obstrace.current()

        def run(r, tag):
            try:
                results.put((tag, self._call(r, path, body, ctx=ctx),
                             None))
            except Exception as e:    # noqa: BLE001 — crosses threads
                results.put((tag, None, e))

        threading.Thread(target=run, args=(rep, "primary"),
                         daemon=True).start()
        try:
            tag, out, exc = results.get(timeout=delay)
        except _queue.Empty:
            rep2 = self._pick(exclude=set(exclude) | {rep.rid})
            if rep2 is None:
                tag, out, exc = results.get()     # nothing to hedge onto
            else:
                self.metrics.count("hedges_total")
                threading.Thread(target=run, args=(rep2, "hedge"),
                                 daemon=True).start()
                tag, out, exc = results.get()
                if exc is not None or out[0] >= 500:
                    # first answer was a failure: the race is still on
                    tag, out, exc = results.get()
        if exc is not None:
            raise exc
        if tag == "hedge":
            self.metrics.count("hedge_wins_total")
        return out

    def route_unary(self, route, path, body, session=None, hedge=False):
        """Dispatch one unary request with bounded cross-replica retry.
        Returns (status, headers, data) for the client.  ``hedge`` only
        for idempotent routes (/v1/infer)."""
        t0 = time.perf_counter()
        self.metrics.accepted(route)
        exclude = set()
        attempts = 0
        last_exc = last_503 = None
        while attempts <= self.retry_budget:
            rep = self._pick_wait(exclude=exclude, session=session)
            if rep is None:
                break
            try:
                if hedge:
                    st, hd, data = self._hedged_call(rep, path, body,
                                                     exclude)
                else:
                    st, hd, data = self._call(rep, path, body)
            except Exception as e:    # noqa: BLE001 — transport/injected
                logger.warning("%s: dispatch to %s failed: %s: %s",
                               self.name, rep.rid, type(e).__name__, e)
                last_exc = e
                exclude.add(rep.rid)
                attempts += 1
                self.metrics.count("retries_total" if route == "infer"
                                   else "failovers_total")
                continue
            if st == 503:
                self._honor_503(rep, hd)
                last_503 = (st, hd, data)
                exclude.add(rep.rid)
                attempts += 1
                continue
            if st >= 500:
                last_exc = RuntimeError(f"replica {rep.rid} answered "
                                        f"{st}")
                exclude.add(rep.rid)
                attempts += 1
                self.metrics.count("retries_total" if route == "infer"
                                   else "failovers_total")
                continue
            self.metrics.observe_response(time.perf_counter() - t0)
            fwd = {k: v for k, v in hd.items()
                   if k.lower() in ("retry-after",)}
            return st, fwd, data
        if last_503 is not None:
            st, hd, data = last_503
            fwd = {k: v for k, v in hd.items()
                   if k.lower() == "retry-after"}
            # internal marker (stripped by the handler): this 503 came
            # FROM a replica — real upstream backpressure, unlike the
            # router's own no-ready-replica 503 below, which must not
            # drive the AIMD multiplicative decrease
            fwd["X-Upstream-Shed"] = "1"
            return st, fwd, data
        if last_exc is not None:
            self.metrics.reject("exhausted")
            return 502, {}, json.dumps(
                {"error": f"all dispatch attempts failed: "
                          f"{type(last_exc).__name__}: {last_exc}"}
            ).encode()
        self.metrics.reject("unready")
        return 503, {"Retry-After": self._retry_after_hint()}, json.dumps(
            {"error": "no ready replica"}).encode()

    # ------------------------------------------------------------ render

    def ready(self):
        now = self._clock()
        with self._lock:
            return any(r.ready and now >= r.not_before
                       and r.breaker.state != "open"
                       for r in self._replicas.values())

    def replica_states(self):
        with self._lock:
            reps = list(self._replicas.values())
        return {
            r.rid: {
                "url": r.base_url, "ready": r.ready,
                "queue_depth": r.queue_depth, "inflight": r.inflight,
                "breaker": r.breaker.state, "role": r.role,
            } for r in reps
        }

    def render_prometheus(self):
        m, n = self.metrics, self.metrics.name
        lines = []

        def emit(metric, value, help_, mtype="counter", labels=""):
            lines.append(f"# HELP {n}_{metric} {help_}")
            lines.append(f"# TYPE {n}_{metric} {mtype}")
            lines.append(f"{n}_{metric}{labels} {value}")

        def emit_labeled(metric, table, help_, label="replica"):
            lines.append(f"# HELP {n}_{metric} {help_}")
            lines.append(f"# TYPE {n}_{metric} counter")
            for k in sorted(table):
                lines.append(f'{n}_{metric}{{{label}="{k}"}} {table[k]}')

        snap = m.snapshot()
        emit_labeled("requests_total", snap["requests_total"],
                     "requests admitted, by route", label="route")
        emit("responses_total", snap["responses_total"],
             "requests answered with an upstream response")
        emit_labeled("rejected_total", snap["rejected"],
                     "requests the router shed, by reason", label="reason")
        emit_labeled("dispatch_total", snap["dispatch_total"],
                     "upstream dispatch attempts, by replica")
        emit_labeled("dispatch_errors_total", snap["dispatch_errors_total"],
                     "upstream dispatch failures, by replica")
        for field, help_ in (
                ("retries_total", "idempotent infer re-dispatches"),
                ("failovers_total", "generate re-dispatches after an "
                                    "upstream failure"),
                ("midstream_failovers_total",
                 "generate failovers with tokens already streamed "
                 "(continuation resubmitted, stream stayed bit-identical)"),
                ("hedges_total", "hedged infer requests fired"),
                ("hedge_wins_total", "hedged requests answered first"),
                ("client_disconnects_total",
                 "downstream streams dropped by the client (upstream "
                 "closed so the replica reclaims the slot)"),
                ("tokens_proxied_total", "generation tokens streamed "
                                         "through the router")):
            emit(field, snap[field], help_)
        emit_labeled("kv_handoffs_total", snap["kv_handoffs_total"],
                     "disaggregated prefill->decode KV handoffs resolved "
                     "through this router, by outcome (serving/"
                     "transfer.py)", label="outcome")
        emit("kv_handoff_bytes_total", snap["kv_handoff_bytes_total"],
             "KV chain bytes shipped replica-to-replica for handoffs "
             "this router brokered")
        lines.append(f"# HELP {n}_kv_handoff_seconds receive-side "
                     "fetch+verify+deliver latency of brokered KV "
                     "handoffs, recent-window quantiles")
        lines.append(f"# TYPE {n}_kv_handoff_seconds summary")
        for q, v in m.kv_handoff.percentiles(_QUANTILES).items():
            lines.append(f'{n}_kv_handoff_seconds{{quantile="0.{q}"}} '
                         f"{v:.6f}")
        lines.append(f"{n}_kv_handoff_seconds_count {m.kv_handoff.count}")
        emit_labeled("ejections_total", snap["ejections_total"],
                     "replicas ejected from rotation (consecutive "
                     "dispatch failures)")
        emit_labeled("readmissions_total", snap["readmissions_total"],
                     "ejected replicas readmitted by a half-open probe")
        lines.append(f"# HELP {n}_latency_seconds request wall latency at "
                     "the router, recent-window quantiles")
        lines.append(f"# TYPE {n}_latency_seconds summary")
        for q, v in m.latency.percentiles(_QUANTILES).items():
            lines.append(f'{n}_latency_seconds{{quantile="0.{q}"}} '
                         f"{v:.6f}")
        lines.append(f"{n}_latency_seconds_count {m.latency.count}")
        lines.append(f"# HELP {n}_ttft_seconds fleet-wide time to first "
                     "token as routed clients feel it, recent-window "
                     "quantiles")
        lines.append(f"# TYPE {n}_ttft_seconds summary")
        for q, v in m.ttft.percentiles(_QUANTILES).items():
            lines.append(f'{n}_ttft_seconds{{quantile="0.{q}"}} {v:.6f}')
        lines.append(f"{n}_ttft_seconds_count {m.ttft.count}")
        from paddle_tpu.serving.metrics import BREAKER_STATES
        states = self.replica_states()
        for metric, key, help_ in (
                ("replica_ready", "ready", "last /readyz verdict "
                                           "(1 ready / 0 not)"),
                ("replica_queue_depth", "queue_depth",
                 "last polled queue depth"),
                ("replica_inflight", "inflight",
                 "router-side in-flight requests")):
            lines.append(f"# HELP {n}_{metric} {help_}")
            lines.append(f"# TYPE {n}_{metric} gauge")
            for rid in sorted(states):
                v = states[rid][key]
                lines.append(f'{n}_{metric}{{replica="{rid}"}} {int(v)}')
        lines.append(f"# HELP {n}_replica_breaker_state outlier-ejection "
                     "breaker (0 closed, 1 half-open, 2 open)")
        lines.append(f"# TYPE {n}_replica_breaker_state gauge")
        for rid in sorted(states):
            lines.append(
                f'{n}_replica_breaker_state{{replica="{rid}"}} '
                f"{BREAKER_STATES.get(states[rid]['breaker'], 0)}")
        # contributed sections: the overload controller's overload_*/
        # brownout_* lines, plus anything registered on
        # extra_render_fns (the autoscaler's autoscaler_* lines)
        for fn in list(self.extra_render_fns):
            try:
                lines.extend(fn())
            except Exception:   # noqa: BLE001 — a dying contributor
                pass            # must not kill /metrics
        return "\n".join(lines) + "\n"

    # ------------------------------------------------------------ serve

    def start(self, host="127.0.0.1", port=0):
        """Bind the router's HTTP front-end (port 0 = ephemeral) and
        serve it on a daemon thread; returns the httpd (``.port`` holds
        the bound port)."""
        httpd = ThreadingHTTPServer((host, port), RouterHandler)
        httpd.daemon_threads = True
        httpd.router = self
        httpd.port = httpd.server_address[1]
        self._httpd = httpd
        threading.Thread(target=httpd.serve_forever, daemon=True,
                         name=f"{self.name}-http").start()
        logger.info("%s: routing on http://%s:%d", self.name, host,
                    httpd.port)
        return httpd

    def close(self):
        self._closed.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # the request's root span (obs/trace.py); NULL outside do_POST or
    # with tracing disabled
    _obs = obstrace.NULL
    # final status code sent downstream this request (drives the AIMD
    # release: 429/503 = upstream congestion, 200 = clean completion)
    _status = None
    # True when this request's shedding response originated at a REPLICA
    # (real backpressure) rather than the router itself
    _upstream_shed = False
    # streaming outcome: None for unary, True when the done record went
    # out, False when the stream broke after headers (status frozen at
    # 200 — must not count as a completion for AIMD/drain-rate)
    _stream_ok = None

    def log_message(self, fmt, *args):
        logger.debug("router http: " + fmt, *args)

    def _reply(self, code, payload, content_type="application/json",
               headers=None):
        body = (payload if isinstance(payload, bytes)
                else json.dumps(payload).encode())
        self._status = code
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if self._obs.trace_id:
            self.send_header("X-Trace-Id", self._obs.trace_id)
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    # ------------------------------------------------------------ GET

    def do_GET(self):
        # keep-alive: drop any previous POST's span before replying
        self._obs = obstrace.NULL
        router = self.server.router
        if self.path == "/healthz":
            self._reply(200, {"status": "ok",
                              "replicas": router.replica_states()})
        elif self.path == "/readyz":
            if router.ready():
                self._reply(200, {"status": "ready"})
            else:
                self._reply(503, {"status": "unready",
                                  "reasons": ["no_ready_replica"]},
                            headers={"Retry-After":
                                     router._retry_after_hint()})
        elif self.path == "/metrics":
            self._reply(200, router.render_prometheus().encode(),
                        content_type="text/plain; version=0.0.4")
        elif self.path == "/debug/traces":
            self._reply(200, obstrace.debug_payload())
        else:
            self._reply(404, {"error": f"no route {self.path!r}"})

    # ------------------------------------------------------------ POST

    def _read_body(self):
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length)

    def do_POST(self):
        # the fleet-wide request root: a downstream traceparent (another
        # tier above us) continues that trace, a direct client starts
        # one; every dispatch/leg below parents here and forwards the
        # trace to the replicas.
        ctx = obstrace.extract(self.headers.get("traceparent"))
        with obstrace.span("router.request", ctx=ctx, root=True,
                           route=self.path) as sp, \
                log_context(trace_id=sp.trace_id,
                            request_id=sp.span_id):
            self._obs = sp
            self._route_post()

    def _route_post(self):
        from paddle_tpu.serving.overload import (OverloadController,
                                                 ShedError)
        router = self.server.router
        if self.path not in ("/v1/infer", "/v1/generate"):
            self._reply(404, {"error": f"no route {self.path!r}"})
            return
        body = self._read_body()
        req = None
        if self.path == "/v1/generate":
            try:
                req = json.loads(body)
                assert isinstance(req, dict)
            except Exception:   # noqa: BLE001 — malformed: a replica
                req = None      #                 will 400 it
        # adaptive overload control (serving/overload.py): one permit
        # per request, held across every retry/failover leg.  Priority
        # rides the body ("priority") or the X-Priority header; a shed
        # is an honest 429 with a drain-rate-derived Retry-After.
        priority = OverloadController.parse_priority(
            (req or {}).get("priority") or self.headers.get("X-Priority"))
        deadline_ms = (req or {}).get("deadline_ms")
        if not isinstance(deadline_ms, (int, float)) or deadline_ms <= 0:
            deadline_ms = None
        try:
            router.overload.admit(priority, deadline_ms=deadline_ms)
        except ShedError as e:
            router.metrics.reject("shed")
            self._obs.set(shed=e.reason, priority=priority)
            self._reply(429, {"error": f"overloaded ({e.reason}): {e}",
                              "priority": priority},
                        headers={"Retry-After": e.retry_after_s})
            return
        self._status = None
        # overloaded=True only for REPLICA-origin backpressure: a 429
        # here is always an upstream pass-through (the router's own shed
        # raised above, before the permit existed), a 503 only when the
        # upstream marker says so — the router's own "no ready replica"
        # 503 (a restart window, not congestion) must not collapse the
        # AIMD limit
        self._upstream_shed = False
        self._stream_ok = None
        try:
            self._route_admitted(router, body, req)
        finally:
            st = self._status
            # a stream whose status line froze at 200 but later broke
            # (failover budget exhausted, client gone) is NOT a
            # completion — it must feed neither the drain-rate estimate
            # nor the additive limit increase
            router.overload.release(
                overloaded=st == 429
                or (st == 503 and self._upstream_shed),
                completed=st == 200 and self._stream_ok is not False)

    def _strip_shed_marker(self, hd):
        if hd.pop("X-Upstream-Shed", None) is not None:
            self._upstream_shed = True
        return hd

    def _route_admitted(self, router, body, req):
        if self.path == "/v1/infer":
            st, hd, data = router.route_unary(
                "infer", "/v1/infer", body,
                hedge=router.hedge_ms != 0
                and router.overload.hedging_allowed())
            self._reply(st, data, headers=self._strip_shed_marker(hd))
            return
        session = (req or {}).get("session")
        if not isinstance(session, str):
            session = None          # affinity keys must be hashable strs
        if req is None or not req.get("stream"):
            # brownout rung 2: cap the effective max_tokens of a unary
            # generate before it reaches a replica
            if req is not None \
                    and router.overload.ladder.capping_tokens():
                cur = req.get("max_tokens")
                if not isinstance(cur, int) or cur < 1:
                    from paddle_tpu.utils.flags import FLAGS
                    cur = FLAGS.serving_gen_max_tokens
                req["max_tokens"] = router.overload.cap_max_tokens(cur)
                body = json.dumps(req).encode()
            t_start = time.perf_counter()
            st, hd, data = router.route_unary(
                "generate", "/v1/generate", body, session=session)
            self._strip_shed_marker(hd)
            if st == 200:
                # fleet-wide TTFT as the CLIENT felt it: the replica-
                # reported ttft_ms misses router-side queueing/retry/
                # failover time (exactly the wait the autoscaler must
                # see), so add back everything the router spent beyond
                # the replica's own post-first-token generation time
                try:
                    out = json.loads(data)
                    rep_ttft = out.get("ttft_ms")
                    rep_lat = out.get("latency_ms")
                    if isinstance(rep_ttft, (int, float)):
                        ttft_ms = rep_ttft
                        if isinstance(rep_lat, (int, float)) \
                                and rep_lat >= rep_ttft:
                            elapsed_ms = (time.perf_counter()
                                          - t_start) * 1e3
                            ttft_ms = max(rep_ttft, elapsed_ms
                                          - (rep_lat - rep_ttft))
                        router.metrics.observe_ttft(ttft_ms / 1e3)
                except Exception:   # noqa: BLE001 — advisory only
                    pass
            self._reply(st, data, headers=hd)
            return
        self._generate_stream(router, req, session)

    # ------------------------------------------------- streaming failover

    def _generate_stream(self, router, req, session):
        """Proxy a streaming /v1/generate with CROSS-REPLICA MID-STREAM
        FAILOVER: tokens forwarded so far are tracked; when the upstream
        replica dies before its ``done`` record, the stream resumes on a
        healthy replica as a continuation (``replay`` = prompt-relative
        tokens already delivered) — bit-identical by greedy determinism.
        A client disconnect closes the upstream connection, firing the
        replica's ``abandon()`` slot reclamation."""
        t0 = time.perf_counter()
        m = router.metrics
        m.accepted("generate")
        orig_replay = list(req.get("replay") or [])
        eff_max = req.get("max_tokens")
        if not isinstance(eff_max, int) or eff_max < 1:
            # the replica-side default; the router must know the cap to
            # compute a continuation's remaining budget.  This reads the
            # ROUTER process's flags — bit-identical failover for
            # requests that omit max_tokens requires the replicas to run
            # with the same serving_gen_max_tokens (docs/serving.md §7
            # "Config parity caveat")
            from paddle_tpu.utils.flags import FLAGS
            eff_max = FLAGS.serving_gen_max_tokens
        # brownout rung 2: cap the stream's token budget (greedy decode
        # means the capped stream is a bit-identical PREFIX of the full
        # one — quality degrades to a shorter answer, never a wrong one)
        eff_max = router.overload.cap_max_tokens(eff_max)
        eos_id = req.get("eos_id")
        delivered = []                # NEW tokens forwarded downstream
        state = {"headers_sent": False}   # shared with the leg proxy: a
        # 200 leg that dies before its first token must not let a later
        # leg emit a second status line
        attempts = 0
        exclude = set()
        last_shed = None              # last orderly 503 (status, hd, data)
        # disaggregated prefill/decode (serving/transfer.py;
        # docs/serving.md "Disaggregated serving"): when the ready set
        # holds both roles, split a fresh stream into a PREFILL leg
        # (max_tokens=1 on a prefill-role replica — its done record is
        # the handoff boundary, not the stream's end) and a DECODE leg
        # that ships chain key + continuation; the decode replica pulls
        # the KV blocks over /v1/kv/export.  Any prefill death or
        # transfer failure degrades to the plain continuation-replay
        # path below — recompute, bit-identical by greedy determinism.
        prompt_ids = req.get("prompt")
        disagg = (router.disagg_active()
                  and isinstance(prompt_ids, list) and prompt_ids
                  and all(isinstance(t, int) for t in prompt_ids))
        handoff_src = None        # prefill replica URL once the boundary
        #                           lands (stays attached across decode-
        #                           leg failovers)

        def send_headers():
            if state["headers_sent"]:
                return
            state["headers_sent"] = True
            self._status = 200
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            if self._obs.trace_id:
                self.send_header("X-Trace-Id", self._obs.trace_id)
            self.end_headers()

        def chunk(obj):
            data = (json.dumps(obj) + "\n").encode()
            self.wfile.write(f"{len(data):X}\r\n".encode() + data
                             + b"\r\n")

        def finish(done_rec):
            out = dict(done_rec)
            # the decode replica reports how its leg got the context
            # (serving/transfer.py outcome dict) — fold it into the
            # router's fleet-wide handoff counters/latency histogram
            hand = out.get("kv_handoff")
            if isinstance(hand, dict) and hand.get("outcome"):
                ms = hand.get("ms")
                m.observe_kv_handoff(
                    hand["outcome"], hand.get("bytes") or 0,
                    ms / 1e3 if isinstance(ms, (int, float)) else None)
            out["tokens"] = list(delivered)
            out["latency_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
            chunk(out)
            self.wfile.write(b"0\r\n\r\n")
            self._stream_ok = True
            m.observe_response(time.perf_counter() - t0)

        def fail_stream(msg):
            self._stream_ok = False
            if not state["headers_sent"]:
                self._reply(502, {"error": msg})
                return
            try:
                chunk({"error": msg})
                self.wfile.write(b"0\r\n\r\n")
            except Exception:   # noqa: BLE001 — client gone too
                pass
            self.close_connection = True

        while True:
            # a finished stream needs no upstream at all: synthesize the
            # done record (a failover can land exactly on the boundary)
            if delivered and eos_id is not None \
                    and delivered[-1] == eos_id:
                send_headers()
                finish({"done": True, "finish_reason": "eos",
                        "ttft_ms": None})
                return
            if len(delivered) >= eff_max:
                send_headers()
                finish({"done": True, "finish_reason": "length",
                        "ttft_ms": None})
                return
            if attempts > router.retry_budget:
                m.reject("exhausted")
                fail_stream("stream failover budget exhausted")
                return
            prefer = None
            if disagg:
                prefer = ("prefill" if not delivered
                          and handoff_src is None else "decode")
            # the prefill leg ignores session affinity (the session
            # belongs with the decode replica that will own the stream)
            rep = router._pick_wait(
                exclude=exclude,
                session=None if prefer == "prefill" else session,
                prefer_role=prefer)
            if rep is None:
                if last_shed is not None and not state["headers_sent"]:
                    st, hd, data = last_shed
                    self._upstream_shed = True    # replica-origin 503
                    self._reply(st, data,
                                headers={k: v for k, v in hd.items()
                                         if k.lower() == "retry-after"})
                    return
                m.reject("unready")
                if not state["headers_sent"]:
                    self._reply(503, {"error": "no ready replica"},
                                headers={"Retry-After":
                                         router._retry_after_hint()})
                else:
                    fail_stream("no ready replica for mid-stream "
                                "failover")
                return
            leg = dict(req)
            leg["stream"] = True
            boundary_leg = disagg and prefer == "prefill"
            if boundary_leg:
                # stop at the first token: the prefill leg's done record
                # is the handoff boundary, swallowed below — the decode
                # leg continues the stream
                leg["max_tokens"] = 1
            else:
                leg["max_tokens"] = eff_max - len(delivered)
            replay = orig_replay + delivered
            if replay:
                leg["replay"] = replay
            elif "replay" in leg:
                del leg["replay"]
            if handoff_src is not None and not boundary_leg \
                    and handoff_src != rep.base_url:
                # ship the chain key: the decode replica pulls the
                # prefill replica's KV blocks over /v1/kv/export before
                # admission (a failed pull is its recompute fallback)
                leg["kv_handoff"] = {
                    "source": handoff_src,
                    "tokens": [int(t) for t in prompt_ids] + orig_replay}
            elif "kv_handoff" in leg:
                # never forward a client-supplied hint past the replica
                # that already owns the context
                del leg["kv_handoff"]
            with router._lock:
                rep.inflight += 1
            try:
                # one upstream leg = one span: a failed-over stream shows
                # leg[replica=r0] then leg[replica=r1] on the same trace
                with obstrace.span("router.leg", replica=rep.rid,
                                   attempt=attempts, replay=len(replay),
                                   boundary=boundary_leg):
                    outcome = self._proxy_leg(
                        router, rep, leg, delivered, send_headers, chunk,
                        (lambda rec: None) if boundary_leg else finish,
                        t0)
            finally:
                with router._lock:
                    rep.inflight -= 1
            if outcome[0] == "done":
                router._record(rep, ok=True)
                if boundary_leg:
                    # the 1-token prefill leg completed: this is the
                    # HANDOFF, not the stream's end — loop into the
                    # decode leg with the chain key attached
                    handoff_src = rep.base_url
                    self._obs.event("kv_handoff_boundary",
                                    replica=rep.rid)
                    continue
                return
            if outcome[0] == "client_gone":
                # the downstream reader left: upstream already closed
                # (abandon() fires on the replica); nothing more to say.
                # Not a completion — the work was abandoned, not drained
                self._stream_ok = False
                m.count("client_disconnects_total")
                router._record(rep, ok=True)
                self.close_connection = True
                return
            if outcome[0] == "shed":       # orderly 503 before any bytes
                router._record(rep, ok=True)
                router._honor_503(rep, outcome[1])
                last_shed = (503, outcome[1], outcome[2])
                exclude.add(rep.rid)
                attempts += 1
                continue
            if outcome[0] == "client_error":   # 4xx pass-through
                router._record(rep, ok=True)
                st, hd, data = outcome[1:]
                if state["headers_sent"]:
                    # a failover leg got rejected AFTER the 200 + chunked
                    # headers went out: the status line is spent, so end
                    # the stream with an orderly error record instead of
                    # writing a second status line into the body
                    fail_stream(f"failover leg rejected with {st}: "
                                f"{data.decode(errors='replace')[:200]}")
                else:
                    # a replica-origin 429 (its generation queue is
                    # full) is a SHED: the Retry-After must survive the
                    # pass-through — every shed is an honest 429
                    if st == 429:
                        self._upstream_shed = True
                    self._reply(st, data,
                                headers={k: v for k, v in hd.items()
                                         if k.lower() == "retry-after"})
                return
            # upstream failed (transport death, 5xx, error record):
            # charge the breaker and fail over with the delivered prefix
            router._record(rep, ok=False)
            exclude.add(rep.rid)
            attempts += 1
            if delivered:
                m.count("midstream_failovers_total")
                self._obs.event("midstream_failover", replica=rep.rid,
                                delivered=len(delivered))
                logger.warning(
                    "%s: replica %s died mid-stream after %d token(s); "
                    "failing over with a continuation", router.name,
                    rep.rid, len(delivered))
            m.count("failovers_total")

    def _proxy_leg(self, router, rep, leg, delivered,
                   send_headers, chunk, finish, t0):
        """One upstream streaming leg.  Returns a tagged outcome:
        ("done",) — the stream completed downstream;
        ("client_gone",) — the downstream client dropped;
        ("shed", headers, body) — orderly 503 before any stream bytes;
        ("client_error", status, headers, body) — 4xx pass-through;
        ("pre", reason) — upstream failed before this leg streamed;
        ("mid", reason) — upstream failed after this leg streamed."""
        m = router.metrics
        streamed_here = False
        try:
            conn, resp = router._dispatch(rep, "POST", "/v1/generate",
                                          json.dumps(leg).encode(),
                                          stream=True)
        except Exception as e:    # noqa: BLE001 — transport/injected
            return ("pre", f"{type(e).__name__}: {e}")
        try:
            if resp.status != 200:
                data = resp.read()
                hd = dict(resp.getheaders())
                if resp.status == 503:
                    return ("shed", hd, data)
                if resp.status < 500:
                    return ("client_error", resp.status, hd, data)
                return ("pre", f"replica answered {resp.status}")
            send_headers()
            while True:
                line = resp.readline()
                if not line:
                    # upstream EOF without a done record: the replica
                    # died (kill -9 closes the socket mid-chunk)
                    return (("mid" if streamed_here or delivered
                             else "pre"), "upstream EOF before done")
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    return ("mid", "malformed upstream chunk")
                if "token" in rec:
                    delivered.append(int(rec["token"]))
                    if len(delivered) == 1:
                        self._obs.event("first_token")
                        m.observe_ttft(time.perf_counter() - t0)
                    streamed_here = True
                    m.count("tokens_proxied_total")
                    try:
                        chunk({"token": int(rec["token"])})
                    except Exception:   # noqa: BLE001 — client gone:
                        return ("client_gone",)
                elif rec.get("done"):
                    try:
                        finish(rec)
                    except Exception:   # noqa: BLE001
                        return ("client_gone",)
                    return ("done",)
                elif "error" in rec:
                    # replica-side mid-stream failure record: its own
                    # recovery gave up — fail over across replicas
                    return (("mid" if streamed_here or delivered
                             else "pre"),
                            f"upstream error record: {rec['error']}")
        except Exception as e:    # noqa: BLE001 — read failure = death
            return (("mid" if streamed_here or delivered else "pre"),
                    f"{type(e).__name__}: {e}")
        finally:
            # closing the upstream connection is ALSO the disconnect
            # propagation path: an abandoned leg's replica sees the
            # socket close and reclaims the slot at the next token
            conn.close()


# ------------------------------------------------------------------ smoke


def _smoke():
    """Fleet self-test (healthy_window.sh phase 10): 2 tiny demo
    replicas on ephemeral ports behind the router, concurrent streaming
    /v1/generate clients, kill -9 one replica MID-STREAM — every stream
    must finish bit-identical to the local ``lm_generate`` oracle, the
    router must report the failover, and the supervisor must restart the
    victim to readiness.  ONE JSON line; returns the exit code."""
    import numpy as np
    import jax
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.fleet import ReplicaSupervisor

    errs = []
    out = {"metric": "fleet smoke (replica supervisor + health-checked "
                     "router, kill -9 mid-stream)",
           "vs_baseline": None}
    n_clients, n_tokens, max_len = 6, 24, 64
    # the replicas' demo LM (server.py _demo_gen_batcher) — recomputed
    # here for the oracle; the injected decode-step hang paces tokens
    # (~25ms each) so the kill reliably lands MID-stream
    extra = ["--gen-slots", "4", "--gen-max-len", str(max_len),
             "--gen-prefill-buckets", "8,16",
             "--gen-max-tokens", str(n_tokens),
             "--fault-spec",
             "serving.decode_step:every=1,action=hang,hang_s=0.025"]
    sup = ReplicaSupervisor(n_replicas=2, extra_args=extra,
                            backoff_base_s=0.3, seed=0,
                            name="fleet_smoke")
    router = Router(supervisor=sup, poll_interval_s=0.1,
                    eject_threshold=2, eject_cooldown_s=1.0,
                    retry_budget=3, name="router_smoke")
    httpd = None
    try:
        sup.start()
        if not sup.wait_ready(timeout=240):
            errs.append("replicas never became ready")
            raise RuntimeError("fleet warm-up timeout")
        httpd = router.start(port=0)
        deadline = time.monotonic() + 30
        while not router.ready() and time.monotonic() < deadline:
            time.sleep(0.05)
        base = f"http://127.0.0.1:{httpd.port}"

        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, 256, 3 + 2 * i).astype(np.int64)
                   for i in range(n_clients)]
        params = transformer.init(jax.random.PRNGKey(0), src_vocab=256,
                                  trg_vocab=1, d_model=32, num_heads=2,
                                  dff=64, enc_layers=2, dec_layers=0,
                                  max_len=max_len)
        oracle = []
        for p in prompts:
            ids = np.asarray(transformer.lm_generate(
                params, p[None], max_len=max_len, num_heads=2,
                prompt_lengths=np.asarray([p.size])))
            oracle.append(ids[0, p.size:p.size + n_tokens].tolist())

        results = [None] * n_clients
        first_token = threading.Barrier(n_clients + 1, timeout=120)

        def hit(i):
            armed = True
            try:
                conn = http.client.HTTPConnection("127.0.0.1", httpd.port,
                                                  timeout=120)
                conn.request(
                    "POST", "/v1/generate",
                    json.dumps({"prompt": prompts[i].tolist(),
                                "max_tokens": n_tokens,
                                "stream": True}).encode(),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                toks, done = [], None
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    rec = json.loads(line)
                    if "token" in rec:
                        toks.append(rec["token"])
                        if armed and len(toks) >= 2:
                            armed = False
                            first_token.wait()
                    if rec.get("done"):
                        done = rec
                        break
                conn.close()
                if armed:
                    first_token.wait()      # finished before 2 tokens(!)
                results[i] = {"tokens": toks, "done": done}
            except Exception as e:      # noqa: BLE001
                errs.append(f"client {i}: {type(e).__name__}: {e}")
                if armed:
                    try:
                        first_token.wait()
                    except threading.BrokenBarrierError:
                        pass

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        # kill -9 one replica once EVERY stream is visibly mid-decode
        first_token.wait()
        sup.kill("r0", signal.SIGKILL)
        out["victim_killed"] = True
        for t in threads:
            t.join(180)
        ok = sum(1 for r in results if r is not None and r["done"])
        bit_identical = all(
            r is not None and r["tokens"] == oracle[i]
            and r["done"] and r["done"]["tokens"] == oracle[i]
            for i, r in enumerate(results))
        snap = router.metrics.snapshot()
        import urllib.request
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            mtext = r.read().decode()
        out.update(
            streams_ok=ok,
            bit_identical=bool(bit_identical),
            midstream_failovers=snap["midstream_failovers_total"],
            failovers=snap["failovers_total"],
            tokens_proxied=snap["tokens_proxied_total"],
            router_metrics_sane=(
                "midstream_failovers_total" in mtext
                and 'replica_ready{replica="r1"} 1' in mtext),
        )
        # supervision evidence: the victim restarts (backoff) and comes
        # back ready — the router readmits it automatically
        restarted = sup.wait_ready(timeout=240, rids=("r0",))
        fsnap = sup.snapshot()
        out["restarted_ready"] = bool(restarted)
        out["victim_restarts"] = fsnap["r0"]["restarts_total"]
        out["backoff_delays_s"] = fsnap["r0"]["backoff_delays_s"]
        checks = [
            ok == n_clients,
            bool(bit_identical),
            snap["midstream_failovers_total"] >= 1,
            bool(out["router_metrics_sane"]),
            bool(restarted) and fsnap["r0"]["restarts_total"] >= 1,
        ]
    except Exception as e:      # noqa: BLE001 — a harness failure must
        errs.append(f"smoke: {type(e).__name__}: {e}")
        checks = [False]
    finally:
        try:
            router.close()
        finally:
            sup.stop()
    out["value"] = sum(bool(c) for c in checks)
    out["unit"] = f"checks_ok/{len(checks)}"
    if errs:
        out["errors"] = errs[:5]
    print(json.dumps(out), flush=True)
    return 0 if all(checks) else 2


def _smoke_disagg():
    """Disaggregated-serving self-test (healthy_window.sh phase 21):
    ONE prefill-role + ONE decode-role replica behind the router,
    concurrent streaming clients handed off mid-flight — each new
    prompt prefills on r0, crosses the socket transport at the first
    token (chain key + continuation; the decode replica pulls the KV
    blocks over /v1/kv/export), and decodes on r1.  Every stream must
    finish bit-identical to the local ``lm_generate`` oracle; the
    handoff counters on BOTH replicas' /metrics and the router's must
    prove the blocks really crossed the socket; a short prompt must
    take the analytic recompute fallback; and after kill -9 of the
    prefill replica a handoff against the dead source must fall back to
    recompute, still bit-identical.  ONE JSON line; returns the exit
    code."""
    import urllib.request
    import numpy as np
    import jax
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.fleet import ReplicaSupervisor

    errs = []
    out = {"metric": "disaggregated serving smoke (prefill/decode "
                     "replicas, socket KV handoff, kill -9 fallback)",
           "vs_baseline": None}
    n_tokens, max_len, bs = 24, 64, 8
    # block-aligned prompts: the handed-off chain covers the WHOLE
    # prompt, so the decode replica seats it with zero prefill chunk
    # lanes.  Lengths 32/40 sit above the analytic crossover (handoff
    # beats recompute); 16 sits below it — that stream must take the
    # analytic fallback and still stream bit-identically.
    lengths = [32, 40, 16, 32]
    extra = ["--gen-slots", "4", "--gen-max-len", str(max_len),
             "--gen-prefill-buckets", "8,16",
             "--gen-max-tokens", str(n_tokens),
             "--prefill-chunk", str(bs),
             "--kv-layout", "paged", "--kv-block-size", str(bs),
             "--kv-num-blocks", "49", "--kv-prefix-cache", "1",
             "--kv-host-bytes", str(64 << 20),
             "--fault-spec",
             "serving.decode_step:every=1,action=hang,hang_s=0.015"]
    sup = ReplicaSupervisor(n_replicas=2, roles=("prefill", "decode"),
                            extra_args=extra, backoff_base_s=0.3, seed=0,
                            name="disagg_smoke")
    router = Router(supervisor=sup, poll_interval_s=0.1,
                    eject_threshold=2, eject_cooldown_s=1.0,
                    retry_budget=3, name="router_disagg")

    def outcome_count(text, outcome):
        m = re.search(r'^\S*_kv_handoffs_total\{outcome="'
                      + outcome + r'"\} (\d+)\s*$', text, re.MULTILINE)
        return int(m.group(1)) if m else 0

    def fetch_metrics(url):
        with urllib.request.urlopen(f"{url}/metrics", timeout=30) as r:
            return r.read().decode()

    def stream(port, prompt, replay=None, handoff=None, max_tokens=None):
        """One streaming /v1/generate client; returns (tokens, done)."""
        body = {"prompt": list(map(int, prompt)),
                "max_tokens": (n_tokens if max_tokens is None
                               else max_tokens), "stream": True}
        if replay:
            body["replay"] = list(map(int, replay))
        if handoff is not None:
            body["kv_handoff"] = handoff
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        try:
            conn.request("POST", "/v1/generate",
                         json.dumps(body).encode(),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            toks, done = [], None
            while True:
                line = resp.readline()
                if not line:
                    break
                rec = json.loads(line)
                if "token" in rec:
                    toks.append(rec["token"])
                if rec.get("done"):
                    done = rec
                    break
            return toks, done
        finally:
            conn.close()

    httpd = None
    try:
        sup.start()
        if not sup.wait_ready(timeout=240):
            errs.append("replicas never became ready")
            raise RuntimeError("fleet warm-up timeout")
        eps = dict(sup.endpoints())
        prefill_url, decode_url = eps["r0"], eps["r1"]
        httpd = router.start(port=0)
        # the router must have PROBED both roles before disaggregated
        # routing activates (role rides the /metrics poll)
        deadline = time.monotonic() + 30
        while not router.disagg_active() \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        out["disagg_active"] = router.disagg_active()

        rng = np.random.RandomState(7)
        prompts = [rng.randint(1, 256, n).astype(np.int64)
                   for n in lengths + [32, 32]]   # +kill-fallback, +post
        params = transformer.init(jax.random.PRNGKey(0), src_vocab=256,
                                  trg_vocab=1, d_model=32, num_heads=2,
                                  dff=64, enc_layers=2, dec_layers=0,
                                  max_len=max_len)
        oracle = []
        for p in prompts:
            ids = np.asarray(transformer.lm_generate(
                params, p[None], max_len=max_len, num_heads=2,
                prompt_lengths=np.asarray([p.size])))
            oracle.append(ids[0, p.size:p.size + n_tokens].tolist())

        # ---- phase 1: concurrent streams, handed off mid-flight ----
        results = [None] * len(lengths)

        def hit(i):
            try:
                results[i] = stream(httpd.port, prompts[i])
            except Exception as e:      # noqa: BLE001
                errs.append(f"client {i}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(len(lengths))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        done_ok = sum(1 for r in results
                      if r is not None and r[1] is not None)
        bit_identical = all(
            r is not None and r[0] == oracle[i]
            and r[1] and r[1]["tokens"] == oracle[i]
            for i, r in enumerate(results))
        hand_outcomes = [
            (r[1].get("kv_handoff") or {}).get("outcome")
            if r is not None and r[1] else None for r in results]
        # long prompts (>= the analytic crossover) must have RECEIVED a
        # real handoff; the short one must have fallen back (analytic)
        long_received = all(
            hand_outcomes[i] == "received"
            for i in range(len(lengths)) if lengths[i] >= 32)
        short_fellback = all(
            hand_outcomes[i] == "fallback"
            for i in range(len(lengths)) if lengths[i] < 32)
        pre_text = fetch_metrics(prefill_url)
        dec_text = fetch_metrics(decode_url)
        sent = outcome_count(pre_text, "sent")
        received = outcome_count(dec_text, "received")
        bytes_m = re.search(r"^\S*_kv_handoff_bytes_total (\d+)\s*$",
                            dec_text, re.MULTILINE)
        handoff_bytes = int(bytes_m.group(1)) if bytes_m else 0
        snap = router.metrics.snapshot()
        out.update(
            streams_ok=done_ok,
            bit_identical=bool(bit_identical),
            handoff_outcomes=hand_outcomes,
            prefill_sent=sent,
            decode_received=received,
            decode_handoff_bytes=handoff_bytes,
            router_handoffs=snap["kv_handoffs_total"],
            router_handoff_ms_p50=snap["kv_handoff_ms"].get("p50"),
        )

        # ---- phase 2: kill -9 the prefill replica; a handoff against
        # the dead source must fall back to recompute, bit-identically
        sup.kill("r0", signal.SIGKILL)
        out["victim_killed"] = True
        time.sleep(0.2)                  # let the socket really die
        p_kill, o_kill = prompts[len(lengths)], oracle[len(lengths)]
        dec_port = urlsplit(decode_url).port
        toks, done = stream(
            dec_port, p_kill, replay=o_kill[:1], max_tokens=n_tokens - 1,
            handoff={"source": prefill_url,
                     "tokens": list(map(int, p_kill))})
        kill_hand = (done or {}).get("kv_handoff") or {}
        kill_fallback_ok = (done is not None
                            and toks == o_kill[1:]
                            and done["tokens"] == o_kill[1:]
                            and kill_hand.get("outcome") == "fallback")
        out["kill_fallback_outcome"] = kill_hand
        fallbacks_after = outcome_count(fetch_metrics(decode_url),
                                        "fallback")
        out["decode_fallbacks"] = fallbacks_after

        # ---- phase 3: the fleet keeps serving THROUGH the kill — a
        # fresh stream via the router (its view of r0 may still be
        # stale) must complete bit-identically on what's left
        p_post, o_post = prompts[len(lengths) + 1], oracle[len(lengths) + 1]
        toks3, done3 = stream(httpd.port, p_post)
        post_ok = (done3 is not None and toks3 == o_post
                   and done3["tokens"] == o_post)
        out["post_kill_stream_ok"] = bool(post_ok)

        checks = [
            bool(out["disagg_active"]),
            done_ok == len(lengths),
            bool(bit_identical),
            bool(long_received) and bool(short_fellback),
            sent >= 3 and received >= 3 and handoff_bytes > 0,
            snap["kv_handoffs_total"].get("received", 0) >= 3
            and snap["kv_handoffs_total"].get("fallback", 0) >= 1,
            bool(kill_fallback_ok) and fallbacks_after >= 2,
            bool(post_ok),
        ]
    except Exception as e:      # noqa: BLE001 — a harness failure must
        errs.append(f"smoke: {type(e).__name__}: {e}")
        checks = [False]
    finally:
        try:
            router.close()
        finally:
            sup.stop()
    out["value"] = sum(bool(c) for c in checks)
    out["unit"] = f"checks_ok/{len(checks)}"
    if errs:
        out["errors"] = errs[:5]
    print(json.dumps(out), flush=True)
    return 0 if all(checks) else 2


# -------------------------------------------------------------------- CLI


def main(argv=None):
    from paddle_tpu.utils.flags import FLAGS
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.router",
        description="health-checked router over serving replicas "
                    "(docs/serving.md §7)")
    ap.add_argument("--replicas", type=int, default=FLAGS.fleet_replicas,
                    help="spawn a managed fleet of N demo-generate "
                         "replicas (serving/fleet.py)")
    ap.add_argument("--replica-arg", action="append", default=[],
                    help="extra argv appended to each managed replica "
                         "(repeatable)")
    ap.add_argument("--backends",
                    help="comma-separated replica base URLs (externally "
                         "managed; overrides --replicas)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=FLAGS.router_port)
    ap.add_argument("--poll-interval-s", type=float,
                    default=FLAGS.router_poll_interval_s)
    ap.add_argument("--unready-grace-s", type=float,
                    default=FLAGS.router_unready_grace_s)
    ap.add_argument("--eject-threshold", type=int,
                    default=FLAGS.router_eject_threshold)
    ap.add_argument("--eject-cooldown-s", type=float,
                    default=FLAGS.router_eject_cooldown_s)
    ap.add_argument("--retry-budget", type=int,
                    default=FLAGS.router_retry_budget)
    ap.add_argument("--hedge-ms", type=float, default=FLAGS.router_hedge_ms)
    ap.add_argument("--fault-spec", default=FLAGS.resilience_fault_spec,
                    help="deterministic fault plan (router.dispatch is "
                         "the router-layer point; chaos testing only)")
    ap.add_argument("--obs-trace",
                    type=lambda v: v.lower() in ("1", "true", "yes"),
                    default=FLAGS.obs_trace_enable,
                    help="per-request span tracing (obs/trace.py): "
                         "/debug/traces + traceparent propagation to "
                         "the replicas")
    ap.add_argument("--obs-trace-sample", type=float,
                    default=FLAGS.obs_trace_sample)
    ap.add_argument("--obs-trace-ring", type=int,
                    default=FLAGS.obs_trace_ring)
    ap.add_argument("--smoke", action="store_true",
                    help="fleet self-test (2 replicas, kill -9 one "
                         "mid-stream), one JSON line, exit")
    ap.add_argument("--smoke-disagg", action="store_true",
                    help="disaggregated-serving self-test (1 prefill + "
                         "1 decode replica, socket KV handoff at the "
                         "first token, analytic fallback, kill -9 of "
                         "the prefill replica), one JSON line, exit")
    args = ap.parse_args(argv)
    if args.smoke:
        return _smoke()
    if args.smoke_disagg:
        return _smoke_disagg()
    if args.fault_spec:
        faults.install_spec(args.fault_spec)
        logger.warning("fault injection ACTIVE: %s", args.fault_spec)
    if args.obs_trace:
        obstrace.enable(sample=args.obs_trace_sample,
                        capacity=args.obs_trace_ring, process="router")
    sup = None
    if args.backends:
        router = Router(replicas=[u.strip() for u in
                                  args.backends.split(",") if u.strip()],
                        poll_interval_s=args.poll_interval_s,
                        unready_grace_s=args.unready_grace_s,
                        eject_threshold=args.eject_threshold,
                        eject_cooldown_s=args.eject_cooldown_s,
                        retry_budget=args.retry_budget,
                        hedge_ms=args.hedge_ms)
    else:
        from paddle_tpu.serving.fleet import ReplicaSupervisor
        sup = ReplicaSupervisor(n_replicas=args.replicas,
                                extra_args=args.replica_arg).start()
        router = Router(supervisor=sup,
                        poll_interval_s=args.poll_interval_s,
                        unready_grace_s=args.unready_grace_s,
                        eject_threshold=args.eject_threshold,
                        eject_cooldown_s=args.eject_cooldown_s,
                        retry_budget=args.retry_budget,
                        hedge_ms=args.hedge_ms)
    router.start(args.host, args.port)     # serves on a daemon thread
    stop = threading.Event()

    def _drain(signum, frame):
        logger.info("SIGTERM: stopping router%s",
                    " + fleet" if sup is not None else "")
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _drain)
        signal.signal(signal.SIGINT, _drain)
    except ValueError:
        pass
    try:
        stop.wait()
    finally:
        router.close()
        if sup is not None:
            sup.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
