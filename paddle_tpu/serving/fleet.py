"""Supervised replica fleet: spawn, health-check, restart N serving
replicas (docs/serving.md §7).

One serving process (server.py) is one failure domain: a crash, a wedged
drain, or a poisoned engine takes every resident stream with it, and
PR-6's in-process recovery cannot outlive the process.  The fleet tier
runs N independent ``python -m paddle_tpu.serving`` replica SUBPROCESSES
— same model, same flags, own port each — under a supervisor that:

* spawns each replica with ``--port 0 --port-file <path>`` (the replica
  binds an ephemeral port and publishes it atomically), so replicas
  never fight over ports and a restarted replica simply appears at a
  new address;
* watches for crashes (any exit the supervisor did not ask for — a
  kill -9 looks exactly like a device wedge) and restarts with
  EXPONENTIAL BACKOFF plus seeded jitter
  (``min(base * 2**k, max) * uniform(0.5, 1.0)``, one
  ``random.Random(seed)`` stream per replica — deterministic under
  test, de-synchronized in production);
* trips a RESTART-STORM breaker when ``storm_threshold`` crashes land
  within ``storm_window_s`` — a replica that cannot stay up stops being
  restarted (state ``failed``) instead of burning the host on a crash
  loop, mirroring the request-level ``CircuitBreaker``;
* supports ROLLING DRAIN (``drain``/``rolling_restart``): SIGTERM one
  replica at a time — the replica finishes queued work under its drain
  deadline while the router routes around it via ``/readyz`` — then
  respawn and wait ready before touching the next one.  Zero-downtime
  restarts (tests/test_fleet.py pins zero failed requests).

The supervisor owns PROCESS health only; request-level health (readiness
gating, outlier ejection, failover) is the router's job
(serving/router.py) — the two compose through ``endpoints()``.
"""

import os
import random
import signal
import subprocess
import sys
import tempfile
import threading
import time

from paddle_tpu.resilience import faults
from paddle_tpu.utils.logging import logger

# the default replica: the built-in tiny-LM generation server (bring-up/
# smoke); production fleets pass their own cmd/extra_args (--artifacts &c)
DEFAULT_REPLICA_CMD = ("-m", "paddle_tpu.serving", "--demo-generate")

# replica lifecycle states (snapshot()/endpoints() surface)
STATES = ("starting", "running", "backoff", "draining", "failed", "stopped")


class _Replica:
    """One managed replica subprocess (all mutation under the
    supervisor's lock)."""

    def __init__(self, rid, cmd, port_file, log_path, role=None):
        self.rid = rid
        self.cmd = list(cmd)
        self.port_file = port_file
        self.log_path = log_path
        self.role = role                  # disaggregated serving role
        #                                   (prefill|decode|mixed|None)
        self.proc = None
        self.port = None                  # read lazily from port_file
        self.state = "stopped"
        self.started_at = 0.0
        self.restarts_total = 0           # crash-driven respawns
        self.drains_total = 0             # deliberate (rolling) restarts
        self.consecutive_failures = 0     # crashes since last healthy uptime
        self.backoff_delays = []          # applied (jittered) delays, seconds
        self.crash_times = []             # monotonic, for the storm window
        self.next_restart_at = None
        self.expected_exit = False        # drain()/stop() asked for it
        self.storm_tripped = False

    @property
    def base_url(self):
        return (f"http://127.0.0.1:{self.port}"
                if self.port is not None else None)


class ReplicaSupervisor:
    """Spawn + supervise ``n_replicas`` serving subprocesses.

    cmd: argv AFTER the interpreter (default: the built-in
    ``--demo-generate`` server) — ``--port 0 --port-file <path>`` is
    always appended; extra_args: appended before the port args (model/
    scale flags).  backoff_base_s/backoff_max_s: crash-restart schedule;
    storm_threshold/storm_window_s: the restart-storm breaker;
    healthy_uptime_s: a replica alive this long resets its consecutive-
    failure count (the backoff exponent); seed: the jitter streams.
    base_dir: where port files + replica logs live (default: a fresh
    temp dir).  roles: optional per-replica disaggregated-serving roles
    (a sequence matched to r0..rN-1, entries from prefill|decode|mixed
    or None) — each named replica is spawned with ``--role <role>`` and
    KEEPS that role across crash restarts (docs/serving.md
    "Disaggregated serving").
    """

    def __init__(self, n_replicas=2, cmd=None, extra_args=(),
                 backoff_base_s=0.5, backoff_max_s=10.0, storm_threshold=5,
                 storm_window_s=30.0, healthy_uptime_s=5.0, seed=0,
                 env=None, base_dir=None, name="fleet", roles=None):
        if int(n_replicas) < 1:
            raise ValueError("n_replicas must be >= 1")
        self.name = name
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.storm_threshold = int(storm_threshold)
        self.storm_window_s = float(storm_window_s)
        self.healthy_uptime_s = float(healthy_uptime_s)
        self.seed = int(seed)
        self.env = dict(env) if env is not None else dict(os.environ)
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="pt_fleet_")
        os.makedirs(self.base_dir, exist_ok=True)
        base = ([sys.executable]
                + (list(cmd) if cmd is not None
                   else list(DEFAULT_REPLICA_CMD))
                + list(extra_args))
        self._base_cmd = base       # template for add_replica clones
        self._lock = threading.RLock()
        self._stopping = False
        self.replicas = {}
        self._rngs = {}
        roles = list(roles or ())
        for i in range(int(n_replicas)):
            rid = f"r{i}"
            pf = os.path.join(self.base_dir, f"{rid}.port")
            role = roles[i] if i < len(roles) else None
            self.replicas[rid] = _Replica(
                rid, base + (["--role", role] if role else []), pf,
                os.path.join(self.base_dir, f"{rid}.log"), role=role)
            # one seeded jitter stream per replica: deterministic replays
            # under test, de-synchronized restarts in production
            self._rngs[rid] = random.Random(self.seed * 7919 + i)
        self._next_idx = int(n_replicas)    # rids are never reused: a
        #                                     scaled-in then scaled-out
        #                                     replica is a NEW identity
        self._monitor = None

    # ------------------------------------------------------------ lifecycle

    def start(self):
        """Spawn every replica and start the crash monitor (idempotent)."""
        with self._lock:
            self._stopping = False
            for rep in self.replicas.values():
                if rep.proc is None or rep.proc.poll() is not None:
                    if not rep.storm_tripped:
                        self._try_spawn(rep)
            if self._monitor is None or not self._monitor.is_alive():
                self._monitor = threading.Thread(
                    target=self._monitor_loop, daemon=True,
                    name=f"{self.name}-monitor")
                self._monitor.start()
        return self

    def _spawn(self, rep):
        # the fleet.spawn fault point models a replica that fails (or
        # hangs) AT spawn, before it could ever publish a port or answer
        # /readyz — the autoscaler's scale-out chaos case.  An injected
        # error propagates to the caller exactly like a real Popen
        # failure (OSError); _try_spawn turns both into backoff restarts.
        faults.hit("fleet.spawn")
        try:
            os.remove(rep.port_file)
        except OSError:
            pass
        rep.port = None
        log = open(rep.log_path, "ab")
        rep.proc = subprocess.Popen(
            rep.cmd + ["--port", "0", "--port-file", rep.port_file],
            stdout=log, stderr=subprocess.STDOUT, env=self.env)
        log.close()                 # the child holds its own fd now
        rep.started_at = time.monotonic()
        rep.expected_exit = False
        rep.state = "starting"
        logger.info("%s: %s spawned (pid %d)", self.name, rep.rid,
                    rep.proc.pid)

    def _try_spawn(self, rep):
        """_spawn, with a failed spawn (injected fleet.spawn fault, a
        real fork/exec failure) accounted like an instant crash: backoff
        restart or storm trip — never an unhandled exception in the
        monitor thread.  Returns True when the subprocess exists."""
        try:
            self._spawn(rep)
            return True
        except Exception as e:    # noqa: BLE001 — spawn failure == crash
            logger.warning("%s: %s spawn failed: %s: %s", self.name,
                           rep.rid, type(e).__name__, e)
            self._on_spawn_failure(rep, time.monotonic())
            return False

    def _read_port(self, rep):
        if rep.port is None:
            try:
                with open(rep.port_file) as f:
                    rep.port = int(f.read().strip())
                rep.state = "running"
            except (OSError, ValueError):
                pass
        return rep.port

    def _monitor_loop(self):
        while True:
            with self._lock:
                if self._stopping:
                    return
                now = time.monotonic()
                for rep in self.replicas.values():
                    if rep.state in ("starting", "running"):
                        self._read_port(rep)
                        if rep.proc.poll() is None:
                            # alive long enough: the crash streak is over
                            if rep.consecutive_failures \
                                    and now - rep.started_at \
                                    >= self.healthy_uptime_s:
                                rep.consecutive_failures = 0
                        elif not rep.expected_exit:
                            self._on_crash(rep, now)
                    elif rep.state == "backoff" \
                            and now >= rep.next_restart_at:
                        if self._try_spawn(rep):
                            rep.restarts_total += 1
            time.sleep(0.05)

    def _on_crash(self, rep, now):
        """An exit nobody asked for (crash, OOM kill, kill -9): schedule
        a backoff restart, or trip the storm breaker."""
        self._schedule_restart(rep, now, rep.proc.returncode)

    def _on_spawn_failure(self, rep, now):
        """The subprocess never came to exist (fleet.spawn fault, fork/
        exec failure): same backoff/storm accounting as an instant
        crash."""
        rep.state = "backoff"       # there is no proc to poll
        self._schedule_restart(rep, now, "spawn_failed")

    def _schedule_restart(self, rep, now, rc):
        rep.consecutive_failures += 1
        rep.crash_times.append(now)
        in_window = [t for t in rep.crash_times
                     if now - t <= self.storm_window_s]
        if len(in_window) >= self.storm_threshold:
            rep.state = "failed"
            rep.storm_tripped = True
            logger.warning(
                "%s: %s crashed %d times within %.0fs (last rc=%s) — "
                "restart-storm breaker OPEN, giving up on this replica",
                self.name, rep.rid, len(in_window), self.storm_window_s, rc)
            return
        k = rep.consecutive_failures - 1
        delay = min(self.backoff_base_s * (2 ** k), self.backoff_max_s)
        delay *= 0.5 + 0.5 * self._rngs[rep.rid].random()
        rep.backoff_delays.append(delay)
        rep.next_restart_at = now + delay
        rep.state = "backoff"
        logger.warning("%s: %s exited rc=%s (crash #%d); restarting in "
                       "%.2fs", self.name, rep.rid, rc,
                       rep.consecutive_failures, delay)

    # ------------------------------------------------------------ scaling

    def add_replica(self, role=None):
        """Scale-out primitive (serving/autoscaler.py): spawn ONE new
        replica under supervision and return its rid.  The rid is fresh
        (never reuses a removed replica's identity, so the router builds
        a clean view with a fresh breaker).  ``role`` optionally pins a
        disaggregated-serving role (``--role prefill|decode|mixed``) on
        the new replica.  Raises when the spawn itself fails
        (fleet.spawn fault, fork/exec failure) — the caller owns the
        retry policy; nothing is registered on failure, so a failed
        scale-out leaves the fleet exactly as it was."""
        with self._lock:
            if self._stopping:
                raise RuntimeError(f"{self.name} is stopping")
            i = self._next_idx
            rid = f"r{i}"
            pf = os.path.join(self.base_dir, f"{rid}.port")
            rep = _Replica(rid,
                           self._base_cmd
                           + (["--role", role] if role else []), pf,
                           os.path.join(self.base_dir, f"{rid}.log"),
                           role=role)
            self._spawn(rep)        # raises on failure: register nothing
            self._next_idx = i + 1
            self.replicas[rid] = rep
            self._rngs[rid] = random.Random(self.seed * 7919 + i)
        logger.info("%s: scaled OUT to %d replicas (+%s)", self.name,
                    len(self.replicas), rid)
        return rid

    def remove_replica(self, rid, drain_timeout=60.0):
        """Scale-in primitive: gracefully drain the replica (SIGTERM —
        it finishes queued work under its drain deadline while the
        router routes around it), then FORGET it (endpoints()/snapshot()
        no longer list it; the monitor never restarts it).  A replica
        with no live process (spawn failed, backoff, storm-tripped) is
        CLAIMED under the monitor's lock before being forgotten — the
        not-running check and the state flip happen in ONE lock
        acquisition, so the monitor's backoff branch can never respawn
        a replica this removal is about to drop (which would leak an
        orphaned, unsupervised subprocess)."""
        for _ in range(3):
            with self._lock:
                rep = self.replicas.get(rid)
                if rep is None:
                    return
                if rep.proc is None or rep.proc.poll() is not None:
                    # dead/backoff: state leaves the monitor's respawn
                    # set ATOMICALLY with the liveness check
                    rep.expected_exit = True
                    rep.state = "stopped"
                    self.replicas.pop(rid, None)
                    self._rngs.pop(rid, None)
                    n = len(self.replicas)
                    logger.info("%s: scaled IN to %d replicas (-%s, "
                                "was not running)", self.name, n, rid)
                    return
            try:
                self.drain(rid, timeout=drain_timeout, restart=False)
                break
            except RuntimeError:
                # the process exited between the check and the drain
                # (crash, or the monitor replaced it) — re-examine
                continue
        with self._lock:
            rep = self.replicas.pop(rid, None)
            self._rngs.pop(rid, None)
            if rep is not None and rep.proc is not None \
                    and rep.proc.poll() is None:
                # backstop (retry loop exhausted by repeated races): a
                # forgotten replica must never keep a live process
                rep.expected_exit = True
                try:
                    os.kill(rep.proc.pid, signal.SIGTERM)
                except OSError:
                    pass
        logger.info("%s: scaled IN to %d replicas (-%s)", self.name,
                    len(self.replicas), rid)

    # ------------------------------------------------------------ chaos/ops

    def kill(self, rid, sig=signal.SIGKILL):
        """Chaos helper: signal a replica (default kill -9).  The monitor
        sees the crash and schedules the backoff restart."""
        with self._lock:
            rep = self.replicas[rid]
            if rep.proc is not None and rep.proc.poll() is None:
                os.kill(rep.proc.pid, sig)

    def drain(self, rid, timeout=60.0, restart=True):
        """Deliberate rolling-restart step: SIGTERM the replica (it stops
        admissions, finishes queued work under its drain deadline — the
        router routes around it via /readyz meanwhile), wait for exit,
        then respawn.  Not a crash: no backoff, no storm accounting."""
        with self._lock:
            rep = self.replicas[rid]
            proc = rep.proc
            if proc is None or proc.poll() is not None:
                raise RuntimeError(f"{rid} is not running")
            rep.expected_exit = True
            rep.state = "draining"
            os.kill(proc.pid, signal.SIGTERM)
        try:
            proc.wait(timeout)
        except subprocess.TimeoutExpired:
            logger.warning("%s: %s did not drain within %.0fs; killing",
                           self.name, rid, timeout)
            proc.kill()
            proc.wait(10)
        with self._lock:
            rep.drains_total += 1
            if restart and not self._stopping:
                self._try_spawn(rep)
            else:
                rep.state = "stopped"

    def rolling_restart(self, ready_timeout=120.0, drain_timeout=60.0):
        """Zero-downtime restart sweep: one replica at a time — drain,
        respawn, wait until IT answers /readyz 200 — so N-1 replicas
        serve throughout."""
        for rid in sorted(self.replicas):
            self.drain(rid, timeout=drain_timeout, restart=True)
            if not self.wait_ready(timeout=ready_timeout, rids=(rid,)):
                raise RuntimeError(
                    f"{rid} not ready {ready_timeout:.0f}s after its "
                    "rolling restart")

    def stop(self, timeout=30.0):
        """SIGTERM every replica, wait, SIGKILL stragglers.  Idempotent."""
        with self._lock:
            self._stopping = True
            procs = []
            for rep in self.replicas.values():
                rep.expected_exit = True
                if rep.proc is not None and rep.proc.poll() is None:
                    try:
                        os.kill(rep.proc.pid, signal.SIGTERM)
                    except OSError:
                        pass
                    procs.append(rep.proc)
                rep.state = "stopped"
        deadline = time.monotonic() + timeout
        for p in procs:
            try:
                p.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(10)
        if self._monitor is not None:
            self._monitor.join(5)

    # ------------------------------------------------------------ discovery

    def endpoints(self):
        """[(rid, base_url)] of replicas with a live process AND a
        published port — the router's replica set.  Backoff/failed/
        stopped replicas are absent (not merely unready): the router
        must not even health-poll an address nobody listens on."""
        out = []
        with self._lock:
            for rep in self.replicas.values():
                if rep.state in ("starting", "running", "draining") \
                        and rep.proc is not None \
                        and rep.proc.poll() is None:
                    self._read_port(rep)
                    if rep.port is not None:
                        out.append((rep.rid, rep.base_url))
        return out

    def wait_ready(self, timeout=120.0, rids=None, poll_s=0.2):
        """Block until every (selected) replica answers /readyz 200;
        returns True on success, False on timeout."""
        import urllib.request
        want = set(rids if rids is not None else self.replicas)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready = set()
            for rid, url in self.endpoints():
                if rid not in want:
                    continue
                try:
                    with urllib.request.urlopen(f"{url}/readyz",
                                                timeout=5) as r:
                        if r.status == 200:
                            ready.add(rid)
                except Exception:   # noqa: BLE001 — not up yet
                    pass
            if ready >= want:
                return True
            time.sleep(poll_s)
        return False

    # ------------------------------------------------------------ evidence

    def snapshot(self):
        """Per-replica supervision counters (the smoke JSON / /metrics
        evidence): state, port, restarts, drains, backoff delays, storm
        breaker."""
        with self._lock:
            return {
                rep.rid: {
                    "state": rep.state,
                    "role": rep.role,
                    "port": rep.port,
                    "pid": (rep.proc.pid if rep.proc is not None
                            and rep.proc.poll() is None else None),
                    "restarts_total": rep.restarts_total,
                    "drains_total": rep.drains_total,
                    "consecutive_failures": rep.consecutive_failures,
                    "backoff_delays_s": [round(d, 4)
                                         for d in rep.backoff_delays],
                    "storm_tripped": rep.storm_tripped,
                } for rep in self.replicas.values()
            }

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
