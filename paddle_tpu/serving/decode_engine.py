"""Continuous-batching generation: slot-based KV-cache decode engine.

``models/transformer.lm_generate`` decodes one fixed prompt batch end to
end: a single long request holds the whole batch hostage, finished rows
keep burning decode steps until the slowest row is done, and new
requests wait for the entire batch to drain.  This module is the serving
answer (Orca-style iteration-level scheduling over a vLLM-style slot
slab):

* ``DecodeEngine`` — a fixed-shape KV-cache SLAB ``[num_slots, max_len,
  Dkv]`` per layer (``init_lm_cache`` machinery) plus per-slot position
  counters.  ONE jitted decode step (``lm_decode_step_slots``) advances
  every slot by one token; each row runs at its own position, so slots
  hold unrelated requests at unrelated depths.  Admission and eviction
  happen BETWEEN steps, entirely on the host: a freed slot's cache row is
  overwritten wholesale at the next admission, so scheduling never
  touches compiled code and the step traces exactly once at warm-up and
  never again (``expect_traces`` discipline, shared with
  ``InferenceEngine.warmup`` and ``SGD.precompile``).

* ``DecodeEngine(kv_layout="paged")`` — the same engine over a PAGED
  KV cache (docs/serving.md §5): per layer a block POOL ``[num_blocks,
  block_size, Dkv]`` plus per-slot block tables, managed by the
  host-side allocator in ``serving/kv_pool.py`` (free list, per-block
  refcounts, copy-on-write forks, prefix index).  Memory is committed
  per BLOCK as a stream actually grows instead of ``max_len`` up front,
  so mixed-length traffic packs by actual length, and requests sharing
  a prompt prefix map their leading blocks to the SAME physical blocks
  (admission takes references instead of re-prefilling — the vLLM/
  PagedAttention memory tier over the Orca scheduler above).  Still ONE
  jitted step (``lm_decode_step_paged``): the block table is data, not
  shape, so admission/eviction/fork churn never retraces, and greedy
  streams stay bit-identical to the slab and to ``lm_generate``
  (tests/test_kv_pool.py).  The slab stays the default layout.

* ``DecodeEngine(prefill_chunk=K)`` — UNIFIED CHUNKED PREFILL (the
  serving CLI default; docs/serving.md "Chunked prefill"): prompt
  ingestion folds into the one jitted step itself
  (``lm_decode_chunk_slots``/``_paged`` — Sarathi-style chunked
  prefill on the Orca scheduler).  Each step advances a MIX of decode
  rows (1 token) and admitting rows (up to K prompt tokens, re-derived
  emissions swallowed until the last chunk, whose output is the first
  real token).  Tokens, positions AND per-slot lane counts are data,
  so the chunk budget tunes without retracing; there is no admission
  write, no prefill ladder, and no prompt cap below ``max_len`` —
  ONE executable is the whole serving hot path.

* Legacy mode (``prefill_chunk=0``): prefill rides the bucketed
  ``InferenceEngine`` ladder — one engine per prompt-LENGTH bucket
  (each with its own batch-bucket ladder), whose forward is
  ``lm_prefill`` + the last-real-position logits — the exact
  composition ``lm_generate`` uses, so a request's greedy stream is
  bit-identical to running it alone (the parity tests pin this token
  for token).  Prompt compile cost is paid once per (length bucket,
  batch bucket), never per request.

* ``GenerationBatcher`` — the request front: bounded queue, per-request
  deadlines (``DeadlineExceededError`` while queued), admission control
  (``InvalidRequestError`` before the queue, ``OverloadedError`` on a
  full queue), streaming ``on_token`` callbacks, graceful drain, and
  batch-failure isolation (a step failure fails only the requests that
  were in flight; the engine resets and keeps serving).

Greedy decode only (temperature-0 argmax inside the jitted step): the
deterministic serving mode whose numerics the oracle tests can pin.
Sampling stays on ``lm_generate``.
"""

import collections
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.obs import trace as obstrace
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.supervisor import (BreakerOpenError,
                                              WatchdogTimeout)
from paddle_tpu.serving.batcher import (BatchExecutionError,
                                        DeadlineExceededError,
                                        OverloadedError, ShutdownError)
from paddle_tpu.serving.engine import InferenceEngine, InvalidRequestError
from paddle_tpu.quant.kv import KV_DTYPES
from paddle_tpu.quant.weights import weight_shape as _w_shape
from paddle_tpu.serving.kv_pool import (HostTier,
                                        InsufficientBlocksError,
                                        PagedKVState,
                                        RestorePendingError,
                                        WireFormatError,
                                        peek_chain_header,
                                        restore_chain, serialize_chain,
                                        slab_equivalent_blocks)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.testing.trace import expect_traces
from paddle_tpu.utils.error import ConfigError
from paddle_tpu.utils.logging import logger

DEFAULT_PREFILL_BUCKETS = (32, 64)


def _block_chunk(row, j, block_size):
    """Block ``j`` of a prefill cache row ``[bucket, Dkv]`` as an exact
    ``[block_size, Dkv]`` chunk (zero-padded past the bucket — those
    positions are masked until the decode step overwrites them)."""
    piece = np.asarray(row)[j * block_size:(j + 1) * block_size]
    if piece.shape[0] == block_size:
        return piece
    pad = np.zeros((block_size - piece.shape[0],) + piece.shape[1:],
                   piece.dtype)
    return np.concatenate([piece, pad], axis=0)


class DecodeEngine:
    """Slot-based continuous-batching decoder over a decoder-only LM trunk
    (``models/transformer`` params with ``dec_layers=0``).

    params: the trunk pytree; num_slots: concurrent requests the slab
    holds; max_len: slab length — every request must satisfy
    ``len(prompt) + max_tokens <= max_len``; prefill_buckets: prompt-
    length ladder (prompts pad up to the nearest bucket; the top bucket
    caps prompt length); prefill_batch_buckets: the batch ladder each
    prefill engine compiles; eos_id: default stop token (None = run to
    max_tokens; per-request override at submit).

    prefill_chunk: 0 (legacy ladder prefill) or K > 0 — unified chunked
    prefill: prompts ingest through the one decode step as up-to-K-token
    chunks (``[S, K]`` token lanes; docs/serving.md "Chunked prefill").
    prefill_chunk_budget: max teacher-forced lanes one step may feed
    across all slots (0 = unbounded) — pure data, bounds per-step
    prefill work and hence TPOT jitter.

    kv_layout: ``"slab"`` (default — one ``[num_slots, max_len, Dkv]``
    row per slot) or ``"paged"`` (a shared ``[kv_num_blocks,
    kv_block_size, Dkv]`` block pool + per-slot block tables,
    serving/kv_pool.py; docs/serving.md §5).  Paged-only knobs:
    kv_block_size (positions per block); kv_num_blocks (pool size
    including the reserved scratch block 0; 0 = auto-size to the slab
    equivalent ``num_slots * ceil(max_len / block_size) + 1`` — same KV
    bytes, strictly more packable); prefix_cache (share resident prompt-
    prefix blocks across requests, copy-on-write on divergence).

    kv_dtype: ``"float32"`` (default) or ``"int8"`` — quantized serving
    (quant/kv.py; docs/serving.md "Quantized serving"): the cache
    stores int8 K/V + per-(position, head) f32 scale sidecars, every
    scatter-write quantizes on the way in, the fused kernels widen in
    registers, and the paged auto-sizing DOUBLES ``kv_num_blocks`` at
    the same byte budget.  Composable with quantized weights
    (quant/weights.quantize_lm — just pass the quantized params tree).

    Slot lifecycle (docs/serving.md §4): FREE -> (prefill) -> ACTIVE
    -> one emitted token per ``step()`` -> EVICTED (eos | length |
    error | shutdown | pool_exhausted) -> FREE.  All bookkeeping is
    host-side numpy; the device only ever sees the fixed-shape slab/pool
    step and the fixed-shape admission writes.
    """

    def __init__(self, params, *, num_heads=8, num_slots=8, max_len=256,
                 prefill_buckets=DEFAULT_PREFILL_BUCKETS,
                 prefill_batch_buckets=(1, 4), eos_id=None, moe_top_k=2,
                 pos_type="learned", metrics=None, name="lm", warm=True,
                 kv_layout="slab", kv_block_size=16, kv_num_blocks=0,
                 prefix_cache=True, prefill_chunk=0,
                 prefill_chunk_budget=0, kv_dtype="float32",
                 speculate_k=0, draft=None, mesh=None, kv_host_bytes=0):
        from paddle_tpu.models import transformer
        self._transformer = transformer
        if params.get("dec"):
            raise ConfigError(
                "DecodeEngine serves the decoder-only LM trunk "
                "(init dec_layers=0); this params tree has a seq2seq "
                "decoder stack — use generate_cached for that")
        self.params = params
        self.num_heads = int(num_heads)
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.eos_id = eos_id
        self.moe_top_k = moe_top_k
        self.pos_type = pos_type
        self.name = name
        self._metrics = metrics or ServingMetrics()
        # unified chunked prefill (docs/serving.md "Chunked prefill"):
        # prefill_chunk = K > 0 folds prompt ingestion into the ONE
        # jitted decode step — each step advances a mix of decode rows
        # (1 token) and admitting rows (up to K prompt tokens, logits
        # discarded until the last chunk).  The separate prefill
        # InferenceEngine ladder below is the opt-in LEGACY mode
        # (prefill_chunk=0).  prefill_chunk_budget: max teacher-forced
        # lanes per step across all slots (0 = unbounded) — data, not
        # shape, so tuning it never retraces.
        self.prefill_chunk = int(prefill_chunk or 0)
        self.prefill_chunk_budget = int(prefill_chunk_budget or 0)
        if self.prefill_chunk < 0 or self.prefill_chunk > self.max_len:
            raise ConfigError(
                f"prefill_chunk={prefill_chunk} must be in "
                f"[0, max_len={self.max_len}]")
        # speculative decoding (serving/speculative.py; docs/serving.md
        # "Speculative decoding"): a draft trunk proposes up to
        # speculate_k tokens per slot, the ONE chunked step scores them
        # all as verify lanes, and host-side acceptance commits the
        # longest greedily-matched prefix via advance(consumed=).  The
        # draft only ever changes SPEED — acceptance keeps exactly what
        # the target would have emitted, so streams stay bit-identical
        # to lm_generate.  k/acceptance/per-slot draft state are DATA:
        # churn never retraces.
        self.speculate_k = int(speculate_k or 0)
        if self.speculate_k < 0 or self.speculate_k >= self.max_len:
            raise ConfigError(
                f"speculate_k={speculate_k} must be in "
                f"[0, max_len={self.max_len})")
        if self.speculate_k and not self.prefill_chunk:
            raise ConfigError(
                "speculate_k needs the unified chunked step "
                "(prefill_chunk > 0): the verify step IS the chunk "
                "step scoring draft lanes")
        if draft is not None and not self.speculate_k:
            raise ConfigError("a draft trunk without speculate_k > 0 "
                              "would never run")
        if self.speculate_k and draft is None:
            raise ConfigError(
                "speculate_k > 0 needs a draft (a DraftTrunk, or a "
                "params tree to build one from — serving/speculative."
                "make_draft derives one from the target's)")
        # token-lane width: the chunk step's K dimension must hold the
        # larger of a prefill chunk and a full verify span (the
        # committed token + speculate_k draft lanes)
        self._kk = (max(self.prefill_chunk, self.speculate_k + 1)
                    if self.prefill_chunk else 0)
        self.prefill_buckets = tuple(sorted(set(int(b)
                                                for b in prefill_buckets)))
        if not self.prefill_buckets or self.prefill_buckets[0] < 1:
            raise ConfigError(f"bad prefill ladder {prefill_buckets!r}")
        if not self.prefill_chunk \
                and self.prefill_buckets[-1] >= self.max_len:
            # chunked mode never builds the ladder, so its shape cannot
            # invalidate a chunked engine
            raise ConfigError(
                f"prefill bucket top {self.prefill_buckets[-1]} leaves no "
                f"room to generate within max_len={self.max_len}")
        if self.num_slots < 1:
            raise ConfigError("num_slots must be >= 1")
        if kv_layout not in ("slab", "paged"):
            raise ConfigError(f"kv_layout={kv_layout!r} (supported: "
                              "'slab', 'paged')")
        if int(kv_host_bytes) < 0:
            raise ConfigError(
                f"kv_host_bytes={kv_host_bytes} must be >= 0")
        if int(kv_host_bytes) and kv_layout != "paged":
            raise ConfigError(
                "kv_host_bytes needs kv_layout='paged': the host tier "
                "spills evicted prefix-chain blocks")
        if kv_dtype not in KV_DTYPES:
            raise ConfigError(f"kv_dtype={kv_dtype!r} (supported: "
                              f"{KV_DTYPES})")
        self.kv_layout = kv_layout
        # int8 KV (quant/kv.py; docs/serving.md "Quantized serving"):
        # the cache stores int8 K/V + per-(position, head) f32 scale
        # sidecars, the step quantizes scatter-writes on the way in, and
        # the fused kernels widen in registers.  The pytree structure is
        # what threads it — no step-signature change, so the 1-trace/
        # 0-retrace discipline is untouched.
        self.kv_dtype = kv_dtype
        # tensor-parallel sharded decode (docs/serving.md "Sharded
        # decode"): mesh=... runs the ONE chunked step under
        # parallel.sharding.shard_map with head-sharded attention, a
        # head-sharded KV pool (each chip holds its Hkv/n stripe of
        # every slot row / pool block — tables/allocator/prefix-index/
        # CoW stay replicated host data) and vocab-sharded tied
        # embeddings.  Only column-slice-exact tensors shard, so greedy
        # streams are BIT-IDENTICAL to the single-chip twin; wo and the
        # FFN replicate (a row-parallel psum would reorder float sums).
        self.mesh = mesh
        self.mesh_shards = 1
        self._shard_axis = None
        if mesh is not None:
            from paddle_tpu.parallel import sharding as _psh
            from paddle_tpu.parallel.mesh import AXIS_MODEL
            from jax.sharding import NamedSharding
            if AXIS_MODEL not in dict(mesh.shape):
                raise ConfigError(
                    "DecodeEngine(mesh=...) needs a mesh with a "
                    f"'{AXIS_MODEL}' axis "
                    "(parallel.sharding.decode_mesh builds one)")
            if not self.prefill_chunk:
                raise ConfigError(
                    "sharded decode runs on the unified chunked step: "
                    "set prefill_chunk > 0 (the legacy prefill ladder "
                    "is single-chip only)")
            probs = _psh.lm_shard_problems(params, self.num_heads,
                                           int(mesh.shape[AXIS_MODEL]))
            if probs:
                raise ConfigError(
                    f"cannot shard this trunk over the mesh: "
                    + "; ".join(probs))
            self._psh = _psh
            self._shard_axis = AXIS_MODEL
            self.mesh_shards = int(mesh.shape[AXIS_MODEL])
            # place the params ONCE: wq/wk/wv + src_emb (and their int8
            # payload/scale leaves) as stripes, everything else
            # replicated — admission/step/reset all reuse this placement
            pspecs = _psh.lm_decode_param_specs(params, AXIS_MODEL)
            params = jax.tree_util.tree_map(
                lambda l, s: jax.device_put(l, NamedSharding(mesh, s)),
                params, pspecs)
            self.params = params
        self._paged = None
        self._host_tier = None
        self._pending_restores = {}
        if kv_layout == "paged":
            self.block_size = int(kv_block_size)
            if self.block_size < 1:
                raise ConfigError("kv_block_size must be >= 1")
            # kv_num_blocks=0 auto-sizes to the SLAB-EQUIVALENT byte
            # budget — int8 blocks are small enough that the same budget
            # holds 2x the count, and a mesh multiplies by n: each chip
            # stores only its Hkv/n stripe of a block, so the PER-CHIP
            # budget holds n× the blocks (slab_equivalent_blocks)
            num_blocks = (int(kv_num_blocks) if kv_num_blocks
                          else slab_equivalent_blocks(
                              self.num_slots, self.max_len,
                              self.block_size, kv_dtype,
                              mesh_shards=self.mesh_shards))
            # hierarchical KV (docs/serving.md "Hierarchical KV"):
            # kv_host_bytes > 0 attaches an LRU host-RAM spill tier —
            # prefix chains evicted under pool pressure serialize to
            # host blobs instead of being destroyed, and the next hit
            # restores them over the host link when the analytic model
            # says that beats recomputing (perf/analytic.py)
            if int(kv_host_bytes):
                if not prefix_cache:
                    raise ConfigError(
                        "kv_host_bytes needs the prefix cache: the host "
                        "tier spills/restores prefix-index chains")
                if mesh is not None:
                    raise ConfigError(
                        "kv_host_bytes is single-chip for now: a sharded "
                        "pool's blocks are head stripes, and the "
                        "cross-replica payload transport is ROADMAP "
                        "item 2(b)")
                self._host_tier = HostTier(cap_bytes=int(kv_host_bytes))
            # host allocator + prefix index + per-slot block tables
            self._paged = PagedKVState(
                self.num_slots, num_blocks, self.block_size, self.max_len,
                prefix_cache=prefix_cache,
                on_evict=self._spill_chain if self._host_tier is not None
                else None)
            # per-layer [num_blocks, block_size, Dkv] pools (block 0 is
            # the scratch block free slot rows point at)
            self._cache = self._place_cache(
                transformer.init_lm_cache_paged(
                    params, num_blocks, self.block_size,
                    max_len=self.max_len, kv_dtype=kv_dtype,
                    num_heads=self.num_heads))
            # host-tier restore bookkeeping (``_pending_restores``: one
            # in-flight marker per prefix key -> (epoch at submit,
            # t_submit) — poll_restores drops a job whose epoch went
            # stale, its claim having died with the old paged state).
            # The trunk signature fences blob relocation to identical
            # trunks; the param count/bytes feed the restore-vs-
            # recompute model.
            enc = params.get("enc") or []
            d = int(_w_shape(params["src_emb"])[1])
            dkv = int(_w_shape(enc[0]["attn"]["wk"])[1]) if enc else 0
            self._kv_dims = (len(enc), dkv)
            self._trunk_sig = (f"L{len(enc)}.d{d}.dkv{dkv}"
                               f".h{self.num_heads}.{kv_dtype}"
                               f".b{self.block_size}")
            leaves = jax.tree_util.tree_leaves(params)
            self._param_count = sum(int(l.size) for l in leaves)
            self._param_bytes = sum(
                int(l.size) * np.dtype(l.dtype).itemsize for l in leaves)
            # the staging job (transfer thread) rebuilds per-block chunk
            # pytrees matching the cache structure WITHOUT touching the
            # live (donated) cache: structure and leaf names are frozen
            # here, once — they are reset-stable (same init fn)
            flat = jax.tree_util.tree_flatten_with_path(self._cache)
            self._cache_leaf_names = [jax.tree_util.keystr(p)
                                      for p, _l in flat[0]]
            self._cache_treedef = flat[1]
        else:
            # init_lm_cache validates max_len against the positional table
            self._cache = self._place_cache(transformer.init_lm_cache(
                params, self.num_slots, self.max_len, kv_dtype=kv_dtype,
                num_heads=self.num_heads))
        # prefill-compute ledger: real positions run through the prefill
        # ladder (the paged prefix cache's whole point is to NOT grow
        # this; bench.py serving_paged reads it for the elimination rate)
        self.prefill_positions_total = 0
        # host-side slot state: token(s) fed at the NEXT step and the
        # position lane 0 sits at; free slots idle at (0, 0) — their
        # compute is discarded and their cache row is overwritten at
        # admission.  Chunked mode widens the token row to K lanes and
        # adds the per-slot lane count (_len — per-slot variable
        # advance, the generalized position counter).
        if self.prefill_chunk:
            self._tokens = np.zeros((self.num_slots, self._kk),
                                    np.int32)
            self._len = np.ones((self.num_slots,), np.int32)
        else:
            self._tokens = np.zeros((self.num_slots,), np.int32)
            self._len = None
        # draft-side host bookkeeping (speculative mode).  Invariant per
        # active slot: _d_pos + len(_d_feed) == _pos + 1 — every
        # committed token (and nothing else) either sits in the draft
        # cache or waits in the feed.  Rollout writes past the committed
        # stream are NEVER counted: they are re-fed on commit, and the
        # chunk step writes lanes BEFORE attending, so stale draft K/V
        # is overwritten before anything reads it.
        self._draft = None
        if self.speculate_k:
            from paddle_tpu.serving.speculative import DraftTrunk
            if not isinstance(draft, DraftTrunk):
                draft = DraftTrunk(
                    draft, k=self.speculate_k, num_slots=self.num_slots,
                    max_len=self.max_len,
                    chunk=max(self.speculate_k + 2, self.prefill_chunk),
                    num_heads=self.num_heads, moe_top_k=self.moe_top_k,
                    pos_type=self.pos_type, name=f"{self.name}.draft",
                    warm=False, mesh=self.mesh)
            elif draft.mesh_shards != self.mesh_shards:
                raise ConfigError(
                    f"draft trunk spans {draft.mesh_shards} mesh "
                    f"shard(s) but the engine spans {self.mesh_shards}: "
                    "build the DraftTrunk with the engine's mesh (or "
                    "pass the raw draft params and let the engine "
                    "build it)")
            elif (draft.k != self.speculate_k
                  or draft.num_slots != self.num_slots
                  or draft.max_len < self.max_len):
                raise ConfigError(
                    f"draft trunk (k={draft.k}, slots={draft.num_slots}, "
                    f"max_len={draft.max_len}) does not match the engine "
                    f"(k={self.speculate_k}, slots={self.num_slots}, "
                    f"max_len={self.max_len})")
            self._draft = draft
            self._d_feed = [[] for _ in range(self.num_slots)]
            self._d_pos = np.zeros((self.num_slots,), np.int32)
            self._d_last = np.zeros((self.num_slots,), np.int32)
            self._spec_armed = {}      # slot -> k_eff armed for the next step
            self._spec_result = {}     # slot -> accepted emission run
        self._pos = np.zeros((self.num_slots,), np.int32)
        self._free = list(range(self.num_slots))[::-1]   # pop() -> slot 0 first
        # epoch guard: reset() bumps it, step() refuses to commit across
        # a bump — a watchdog-abandoned step finishing LATE (its thread
        # cannot be killed) can never write its cache into a rebuilt
        # slab.  The lock makes {epoch check + cache commit} atomic
        # against {epoch bump + slab rebuild}: without it a stale step
        # could pass the check and then overwrite the fresh slab.
        self._epoch = 0
        self._epoch_lock = threading.Lock()
        self._prefill_batch_buckets = tuple(prefill_batch_buckets)
        self._prefill_engines = {}     # length bucket -> InferenceEngine
        self._step_traces = [0]
        # resolved at warm-up (the step's trace time): did the compiled
        # step take the fused Pallas decode-attention path?
        self.decode_kernels = False

        # all_lanes is a TRACE-TIME constant: a speculating engine's
        # step returns EVERY lane's argmax [S, K] (the verify surface —
        # host acceptance needs the target's pick after each draft
        # lane); a plain chunked engine keeps the last-lane [S] output
        spec = bool(self.speculate_k)
        # inside the sharded step's shard_map the model sees LOCAL head
        # stripes; the single-chip path sees the full count.  Both are
        # trace-time constants.
        axis = self._shard_axis
        heads = (self.num_heads // self.mesh_shards if axis is not None
                 else self.num_heads)
        if self.prefill_chunk and self.kv_layout == "paged":
            def _model(p, cache, tokens, pos, lens, tables):
                logits, cache = transformer.lm_decode_chunk_paged(
                    p, tokens, pos, lens, cache, tables, heads,
                    self.moe_top_k, self.pos_type, all_lanes=spec,
                    shard_axis=axis)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache
            body = self._shard_body(_model, n_data=4)

            def _step_fn(p, cache, tokens, pos, lens, tables):
                self._step_traces[0] += 1  # runs only under tracing
                return body(p, cache, tokens, pos, lens, tables)
        elif self.prefill_chunk:
            def _model(p, cache, tokens, pos, lens):
                logits, cache = transformer.lm_decode_chunk_slots(
                    p, tokens, pos, lens, cache, heads,
                    self.moe_top_k, self.pos_type, all_lanes=spec,
                    shard_axis=axis)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache
            body = self._shard_body(_model, n_data=3)

            def _step_fn(p, cache, tokens, pos, lens):
                self._step_traces[0] += 1  # runs only under tracing
                return body(p, cache, tokens, pos, lens)
        elif self.kv_layout == "paged":
            def _step_fn(p, cache, tokens, pos, tables):
                self._step_traces[0] += 1  # runs only under tracing
                logits, cache = transformer.lm_decode_step_paged(
                    p, tokens, pos, cache, tables, self.num_heads,
                    self.moe_top_k, self.pos_type)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache
        else:
            def _step_fn(p, cache, tokens, pos):
                self._step_traces[0] += 1  # runs only under tracing
                logits, cache = transformer.lm_decode_step_slots(
                    p, tokens, pos, cache, self.num_heads, self.moe_top_k,
                    self.pos_type)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        # donate the cache: the step rewrites one position per row, the
        # rest is carried through — without donation every step would copy
        # the whole slab/pool
        self._jit_step = jax.jit(_step_fn, donate_argnums=(1,))

        def _admit_fn(cache, row, slot):
            self._admit_traces[0] += 1
            return jax.tree_util.tree_map(
                lambda s, r: jax.lax.dynamic_update_slice(
                    s, r[None].astype(s.dtype), (slot, 0, 0)), cache, row)

        self._admit_traces = [0]
        # jax.jit compiles one executable per distinct row prefix length
        # (= prefill bucket); warm-up pays each bucket's trace up front.
        # (slab layout only — paged admission goes through _jit_write)
        self._jit_admit = jax.jit(_admit_fn, donate_argnums=(0,))

        def _write_fn(cache, chunk, bid):
            self._write_traces[0] += 1
            return jax.tree_util.tree_map(
                lambda c, ch: c.at[bid].set(ch.astype(c.dtype)),
                cache, chunk)

        def _copy_fn(cache, src, dst):
            self._copy_traces[0] += 1
            return jax.tree_util.tree_map(
                lambda c: c.at[dst].set(c[src]), cache)

        # paged device ops: ONE fixed [block_size, Dkv] write shape
        # regardless of prompt bucket (one trace total), and the
        # copy-on-write block fork
        self._write_traces = [0]
        self._copy_traces = [0]
        self._jit_write = jax.jit(_write_fn, donate_argnums=(0,))
        self._jit_copy = jax.jit(_copy_fn, donate_argnums=(0,))
        self._warm = False
        if warm:
            self.warmup()

    # --------------------------------------------------- sharded decode

    def _place_cache(self, cache):
        """Shard a fresh KV cache over the mesh: every buffer's trailing
        (head-stripe) axis splits, so each chip holds its ``Hkv/n``
        stripe of every slot row / pool block.  Identity when unsharded.
        Used at construction AND by ``reset()`` — a recovery rebuild
        must come back with the same placement or the warm step would
        recompile."""
        if self._shard_axis is None:
            return cache
        from jax.sharding import NamedSharding
        specs = self._psh.lm_cache_specs(cache, self._shard_axis)
        return jax.tree_util.tree_map(
            lambda l, s: jax.device_put(l, NamedSharding(self.mesh, s)),
            cache, specs)

    def _shard_body(self, fn, n_data):
        """Wrap a chunked step body in ``parallel.sharding.shard_map``
        over the engine's mesh (identity when unsharded).  in_specs:
        the param-stripe tree, the cache-stripe tree, then ``n_data``
        replicated host operands (tokens/pos/lens[/tables]).  The
        replication check is disabled: the tiled all-gathers inside the
        model produce values the checker cannot prove replicated, but
        bit-identity to the twin is pinned by tests, which is the
        stronger guarantee."""
        if self._shard_axis is None:
            return fn
        from jax.sharding import PartitionSpec as _P
        pspecs = self._psh.lm_decode_param_specs(self.params,
                                                 self._shard_axis)
        cspecs = self._psh.lm_cache_specs(self._cache, self._shard_axis)
        return self._psh.shard_map(
            fn, mesh=self.mesh,
            in_specs=(pspecs, cspecs) + (_P(),) * n_data,
            out_specs=(_P(), cspecs), check_vma=False)

    # ------------------------------------------------------------ prefill

    def prefill_bucket_for(self, n):
        """Smallest prompt-length bucket >= n, or None beyond the top."""
        for b in self.prefill_buckets:
            if b >= n:
                return b
        return None

    def _prefill_engine(self, bucket):
        eng = self._prefill_engines.get(bucket)
        if eng is not None:
            return eng
        params, transformer = self.params, self._transformer
        trace_box = [0]

        def fwd(feed):
            trace_box[0] += 1
            # cache at BUCKET length, not slab length: the admission
            # write only needs the prompt prefix, so the device<->host
            # round-trip per admission moves bucket-sized rows instead
            # of max_len-sized ones.  kv_dtype threads through: an int8
            # engine's prefill returns int8 rows + scale sidecars, so
            # the admission write's tree_map dtypes line up
            hidden, cache = transformer.lm_prefill(
                params, feed["prompt"], bucket, self.num_heads,
                self.moe_top_k, self.pos_type,
                kv_dtype=None if self.kv_dtype == "float32"
                else self.kv_dtype)
            # the request's FIRST token comes from its last real
            # position's hidden state — gather BEFORE the d_model x vocab
            # projection, exactly like lm_generate
            h_last = jnp.take_along_axis(
                hidden, (feed["length"] - 1)[:, None, None], axis=1)
            logits0 = transformer._lm_project(params, h_last)[:, 0]
            return {"first_logits": logits0, "cache": cache}

        spec = {"prompt": jax.ShapeDtypeStruct((1, bucket), np.int32),
                "length": jax.ShapeDtypeStruct((1,), np.int32)}
        eng = InferenceEngine(jitted=jax.jit(fwd), feed_spec=spec,
                              buckets=self._prefill_batch_buckets,
                              warm=False, name=f"{self.name}.prefill{bucket}",
                              metrics=self.metrics, trace_box=trace_box)
        self._prefill_engines[bucket] = eng
        return eng

    def prefill(self, prompts, lengths):
        """Run prompts through the length-bucketed prefill ladder.

        prompts: [n, L] int32 (rows padded to a common L <= the ladder
        top; pad value is irrelevant — causal attention plus the decode
        loop's own K/V rewrites keep it out of every real position);
        lengths: [n] real lengths.  Returns (first_tokens [n] np.int32,
        cache_rows: list of n per-layer {"k","v"} host-numpy rows
        [bucket, Dkv] — BUCKET-length prefixes, which is all admission
        writes into the slab; see ``admit``).
        """
        faults.hit("serving.prefill")
        prompts = np.asarray(prompts, np.int32)
        lengths = np.asarray(lengths, np.int32)
        n, t = prompts.shape
        bucket = self.prefill_bucket_for(t)
        if bucket is None:
            raise InvalidRequestError(
                f"prompt length {t} exceeds the prefill ladder top "
                f"{self.prefill_buckets[-1]}")
        if t < bucket:
            prompts = np.concatenate(
                [prompts, np.zeros((n, bucket - t), np.int32)], axis=1)
        self.prefill_positions_total += int(lengths.sum())
        out = self._prefill_engine(bucket).infer(
            {"prompt": prompts, "length": lengths})
        first = np.argmax(out["first_logits"], axis=-1).astype(np.int32)
        rows = [jax.tree_util.tree_map(lambda l, i=i: l[i], out["cache"])
                for i in range(n)]
        return first, rows

    # ------------------------------------------------------------ slots

    @property
    def chunked(self):
        """True when prompt ingestion rides the unified chunked step
        (``prefill_chunk > 0``) instead of the legacy prefill ladder."""
        return self.prefill_chunk > 0

    def _arm(self, slot, token, pos):
        """Point a slot at (token, position) for the next step — the one
        place the two token-state layouts ([S] vs [S, K]) meet."""
        if self.prefill_chunk:
            self._tokens[slot, :] = 0
            self._tokens[slot, 0] = token
            self._len[slot] = 1
        else:
            self._tokens[slot] = token
        self._pos[slot] = pos

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def num_active(self):
        return self.num_slots - len(self._free)

    @property
    def step_trace_count(self):
        """Traces of the slab decode step (the no-retrace discipline:
        exactly 1 after warm-up, flat across admission/eviction churn).
        ``lower()`` is an offline tool and re-stages (+1)."""
        return self._step_traces[0]

    @property
    def ready(self):
        """Readiness (/readyz): the slab step, admission write, and
        prefill ladder are all warm."""
        return self._warm

    @property
    def metrics(self):
        return self._metrics

    @metrics.setter
    def metrics(self, m):
        # rewire the cached prefill engines too, so a metrics swap (the
        # bench's per-drive reset) never strands the prefill plane's
        # batch/latency stats on an orphaned object; the chunk-size
        # gauge is config, so the fresh object inherits it immediately
        self._metrics = m
        m.set_prefill_chunk(self.prefill_chunk)
        m.set_kv_dtype(self.kv_dtype)
        m.set_speculate_k(self.speculate_k)
        m.set_mesh_shards(self.mesh_shards)
        for eng in self._prefill_engines.values():
            eng.metrics = m

    def admit(self, first_token, cache_row, length, tokens=None):
        """Seat one prefilled request and arm the slot at (first_token,
        position=length).  Returns the slot id; raises if no slot is
        free (callers check ``free_slots`` — the batcher never
        over-admits).

        Slab: write the bucket-length cache rows into positions
        [0, bucket) of a free slot's slab row.  The row tail past the
        bucket keeps whatever the previous occupant left there — safe by
        the same argument that covers prompt padding: position p is
        scatter-overwritten by the decode step in the same step that
        first unmasks it.

        Paged: claim ``ceil(length / block_size)`` private blocks, chop
        the prefill rows into block-sized chunks and write each into its
        block (ONE compiled write shape — no per-bucket executables),
        then, when ``tokens`` (the real prefix ids) are given and the
        prefix cache is on, publish the full-block prefixes so later
        requests admit by reference.  Raises ``InsufficientBlocksError``
        (nothing claimed) when the pool is dry — the batcher defers the
        request instead of failing it."""
        if not self._free:
            raise RuntimeError(f"{self.name}: no free decode slot")
        if self.kv_layout == "paged":
            slot = self._free.pop()
            try:
                chain = self._paged.seat_fresh(slot, int(length))
            except InsufficientBlocksError:
                self._free.append(slot)
                raise
            bs = self.block_size
            for j, bid in enumerate(chain):
                chunk = jax.tree_util.tree_map(
                    lambda l, j=j: _block_chunk(l, j, bs), cache_row)
                self._cache = self._jit_write(self._cache, chunk,
                                              np.int32(bid))
            if tokens is not None:
                self._paged.register_prefix(
                    np.asarray(tokens)[:int(length)], slot)
        else:
            slot = self._free.pop()
            self._cache = self._jit_admit(self._cache, cache_row,
                                          np.int32(slot))
        self._arm(slot, first_token, length)
        return slot

    def seat_cached(self, full, covered, chain):
        """Seat one request whose leading ``covered`` positions are
        already RESIDENT in ``chain`` (a prefix-cache hit, paged layout
        only): take shared references on the physical blocks — no
        prefill, no copy — arm the slot at ``pre = min(covered,
        len(full) - 1)`` with ``full[pre]``, and return ``(slot,
        replay_feed)`` where replay_feed is the teacher-forced remainder
        ``full[pre+1:]`` (its re-derived emissions are swallowed by the
        batcher, so the stream is bit-identical to a fresh prefill).
        The slot's first write lands either in a fresh block (divergent
        suffix) or inside the last shared block — which ``prepare_step``
        then copy-on-write forks before the step touches it."""
        if not self._free:
            raise RuntimeError(f"{self.name}: no free decode slot")
        full = np.asarray(full, np.int32)
        pre = min(int(covered), full.size - 1)
        slot = self._free.pop()
        try:
            self._paged.seat_shared(slot, chain, pre + 1)
        except Exception:
            self._free.append(slot)
            raise
        self._arm(slot, full[pre], pre)
        # the draft cache holds NOTHING for this slot (the prefix index
        # is target-only): the covered prefix joins the feed and drains
        # through the draft's chunk ingest before speculation starts
        self._draft_seed(slot, full[:pre + 1])
        return slot, [int(t) for t in full[pre + 1:]]

    def seat_chunked(self, full):
        """Seat one request for CHUNKED ingestion (prefill_chunk > 0):
        arm a free slot at (``full[0]``, position 0) and return
        ``(slot, feed)`` where ``feed = full[1:]`` is what the batcher
        chunk-loads through the unified step (its re-derived emissions
        swallowed until the last token is fed — whose step output IS the
        first real emission).  No prefill ladder, no bulk admission
        write: the slab layout touches no device state at all, and the
        paged layout seats an EMPTY chain that ``prepare_step`` grows
        block by block as the span advances."""
        if not self._free:
            raise RuntimeError(f"{self.name}: no free decode slot")
        full = np.asarray(full, np.int32)
        slot = self._free.pop()
        if self.kv_layout == "paged":
            try:
                self._paged.seat_fresh(slot, 0)
            except InsufficientBlocksError:
                self._free.append(slot)
                raise
        self._arm(slot, full[0], 0)
        self._draft_seed(slot, full[:1])
        return slot, [int(t) for t in full[1:]]

    def load_chunk(self, slot, toks):
        """Arm lanes 1..n of ``slot`` for the NEXT step: after the
        slot's current token, feed ``toks`` (the next teacher-forced
        prompt/replay tokens) in the same step.  Chunked mode only;
        called by the batcher strictly BETWEEN steps — lane counts are
        data, so loading never retraces."""
        n = len(toks)
        if not self.prefill_chunk or n >= self.prefill_chunk:
            raise RuntimeError(
                f"{self.name}: load_chunk({n}) needs prefill_chunk > "
                f"{n} (engine has {self.prefill_chunk})")
        self._tokens[slot, 1:1 + n] = toks
        self._len[slot] = 1 + n
        self.metrics.observe_prefill_chunk(n)

    def chunk_len(self, slot):
        """Lanes the next/current step feeds for ``slot`` (1 = plain
        decode)."""
        return int(self._len[slot]) if self.prefill_chunk else 1

    @property
    def speculating(self):
        """True when a draft trunk is attached (``speculate_k > 0``)."""
        return self._draft is not None

    @property
    def draft(self):
        """The attached ``DraftTrunk`` (None unless speculating)."""
        return self._draft

    def _draft_seed(self, slot, toks):
        """(Re)start a slot's draft bookkeeping: the draft cache holds
        nothing for it yet, so ``toks`` (its committed context so far)
        becomes the feed the next ``speculate`` calls drain through the
        draft's chunk ingest.  Called at every seat and at eviction —
        recovery/re-seat paths rebuild the draft cache through the same
        one mechanism."""
        if self._draft is None:
            return
        self._d_feed[slot] = [int(t) for t in toks]
        self._d_pos[slot] = 0
        self._d_last[slot] = 0
        self._spec_armed.pop(slot, None)
        self._spec_result.pop(slot, None)

    def speculate(self, budgets):
        """ONE batched draft rollout, strictly between steps: drain up
        to a chunk of every active slot's committed-token feed into the
        draft cache, then arm draft lanes for each slot in ``budgets``
        (slot -> remaining emission allowance) whose feed fully drained
        THIS call — the rollout's candidates are only fresh for those.
        Arms lanes 1..k_eff of the verify span (lane 0 stays the
        committed token) with ``k_eff = min(speculate_k, budget - 1,
        room to max_len)`` and returns {slot: k_eff}.  Everything here
        is data — feed lengths, positions, acceptance — so speculation
        churn never retraces the rollout or the step."""
        if self._draft is None:
            return {}
        chunk = self._draft.chunk
        tokens = np.zeros((self.num_slots, chunk), np.int32)
        positions = np.zeros((self.num_slots,), np.int32)
        lengths = np.ones((self.num_slots,), np.int32)
        fed = {}
        free_set = set(self._free)
        for slot in range(self.num_slots):
            if slot in free_set:
                continue
            feed = self._d_feed[slot]
            take = min(chunk, len(feed))
            if take:
                tokens[slot, :take] = feed[:take]
                positions[slot] = self._d_pos[slot]
                lengths[slot] = take
                fed[slot] = take
            else:
                # nothing pending: idempotently re-feed the last
                # ingested token (identical K/V rewrite) instead of
                # special-casing the row out of the fixed-shape call
                tokens[slot, 0] = self._d_last[slot]
                positions[slot] = max(int(self._d_pos[slot]) - 1, 0)
        drafts = self._draft.rollout(tokens, positions, lengths)
        if drafts is None:
            return {}       # reset() raced the rollout: arm nothing
        for slot, take in fed.items():
            self._d_last[slot] = self._d_feed[slot][take - 1]
            del self._d_feed[slot][:take]
            self._d_pos[slot] += take
        armed = {}
        for slot, budget in budgets.items():
            if fed.get(slot) is None or self._d_feed[slot]:
                continue    # feed not fully drained: candidates stale
            k_eff = min(self.speculate_k, int(budget) - 1,
                        self.max_len - 1 - int(self._pos[slot]))
            if k_eff < 1:
                continue
            self._tokens[slot, 1:1 + k_eff] = drafts[slot, :k_eff]
            self._tokens[slot, 1 + k_eff:] = 0
            self._len[slot] = 1 + k_eff
            self._spec_armed[slot] = k_eff
            armed[slot] = k_eff
        return armed

    def take_spec_result(self, slot):
        """Pop the last step's accepted run for ``slot``: the matched
        draft tokens followed by the target's own argmax at the first
        mismatch (so a run is never empty — every verify step nets at
        least the token a plain step would have produced).  None if the
        slot was not speculating that step."""
        if self._draft is None:
            return None
        return self._spec_result.pop(slot, None)

    def register_context(self, slot, tokens):
        """Publish a fully-ingested context's prompt prefix into the
        paged prefix index (chunked admission's twin of the ``admit``
        registration; no-op on slab / with the cache off)."""
        if self.kv_layout == "paged":
            self._paged.register_prefix(np.asarray(tokens, np.int32),
                                        slot)

    # ------------------------------------------------- hierarchical KV tier

    @property
    def host_tier(self):
        """The attached host-RAM spill tier (None unless
        ``kv_host_bytes > 0`` on a paged engine)."""
        return self._host_tier

    def _spill_chain(self, key, covered, chain):
        """``PrefixIndex`` eviction hook: gather the chain's block rows
        off the device (the contents are still owned — the hook fires
        BEFORE the references release), serialize them as a relocatable
        blob (``kv_pool.serialize_chain``), and park it in the host
        tier.  Runs on the batcher worker thread strictly between steps
        (evictions only happen inside ``_alloc``), so the committed
        cache is safe to read."""
        tier = self._host_tier
        # the index registers EVERY full-block prefix of a stream as its
        # own entry, and pool pressure evicts them shortest-first — so a
        # naive hook would serialize the same leading blocks once per
        # prefix length (O(n^2) payload, all on the claim path that is
        # waiting for these very blocks).  A spill is redundant while a
        # LONGER entry of the same stream is still resident (it spills
        # the superset payload if it ever leaves; until then the content
        # is servable from the index itself) or already parked.
        key = tuple(key)
        n = len(key)
        if any(len(k) > n and k[:n] == key
               for k in self._paged.index._entries):
            return
        if tier.covers(key):
            return
        idx = np.asarray(chain, np.int32)
        arrays = [(name, np.asarray(leaf[idx]))
                  for name, leaf in zip(
                      self._cache_leaf_names,
                      jax.tree_util.tree_leaves(self._cache))]
        blob = serialize_chain(key, covered, arrays, self._trunk_sig)
        dropped = tier.put(key, covered, blob)
        self.metrics.observe_kv_spill(len(chain))
        self.metrics.set_host_tier_bytes(tier.bytes)
        obstrace.instant("kv.spill", blocks=len(chain), bytes=len(blob),
                         covered=int(covered), lru_dropped=dropped)

    def _restore_predicted_faster(self, covered):
        """The restore-vs-recompute router (perf/analytic.py): predicted
        wall cost of streaming ``covered`` spilled positions back over
        the host link vs re-running them through chunked prefill, at the
        chip spec matching this backend.  Returns ``(verdict,
        restore_ms, recompute_ms)`` — the ``serving_kv_spill`` bench
        gates both directions of this comparison."""
        from paddle_tpu.perf import analytic
        chip = "cpu" if jax.default_backend() == "cpu" else "v5e"
        layers, dkv = self._kv_dims
        restore = analytic.predicted_restore_ms(
            covered, layers, dkv, self.num_heads, self.kv_dtype, chip)
        # the legacy ladder re-prefills in ONE dispatch — model it as a
        # single whole-prefix chunk step
        k = self.prefill_chunk if self.prefill_chunk else int(covered) + 1
        recompute = analytic.predicted_recompute_ms(
            covered, self._param_count, self._param_bytes, k, chip)
        return restore < recompute, restore, recompute

    def _handoff_predicted_faster(self, covered):
        """The handoff-vs-recompute router (perf/analytic.py): predicted
        wall cost of pulling ``covered`` positions' K/V from a peer
        replica over the network AND restoring them over the host link,
        vs re-running them through chunked prefill here.  Returns
        ``(verdict, handoff_ms, recompute_ms)`` — the ``serving_disagg``
        bench gates both directions of this comparison, exactly like
        ``serving_kv_spill`` gates the local pair."""
        from paddle_tpu.perf import analytic
        chip = "cpu" if jax.default_backend() == "cpu" else "v5e"
        layers, dkv = self._kv_dims
        handoff = analytic.predicted_handoff_ms(
            covered, layers, dkv, self.num_heads, self.kv_dtype, chip)
        k = self.prefill_chunk if self.prefill_chunk else int(covered) + 1
        recompute = analytic.predicted_recompute_ms(
            covered, self._param_count, self._param_bytes, k, chip)
        return handoff < recompute, handoff, recompute

    def export_chain(self, tokens):
        """Serialize the longest resident coverage of ``tokens`` as a
        relocatable wire-format blob for a cross-replica handoff
        (serving/transfer.py).  Worker-thread-only — the gather reads
        the committed cache exactly like ``_spill_chain`` does, so HTTP
        handlers must route through ``GenerationBatcher.export_chain``
        (which queues it to run strictly between steps).  Prefers the
        resident prefix index (read-only lookup, no references taken);
        falls back to an already-serialized host-tier blob.  Returns
        ``(key, covered, blob)`` or ``(None, 0, None)``."""
        if self.kv_layout != "paged":
            return None, 0, None
        full = np.asarray(tokens, np.int32)
        covered, chain = self._paged.lookup_prefix(full)
        if covered:
            key = tuple(int(t) for t in full[:covered])
            idx = np.asarray(chain, np.int32)
            arrays = [(name, np.asarray(leaf[idx]))
                      for name, leaf in zip(
                          self._cache_leaf_names,
                          jax.tree_util.tree_leaves(self._cache))]
            blob = serialize_chain(key, covered, arrays,
                                   self._trunk_sig)
            obstrace.instant("kv.handoff_export", blocks=len(chain),
                             bytes=len(blob), covered=int(covered))
            return key, covered, blob
        if self._host_tier is not None:
            key, covered, blob = self._host_tier.lookup(full,
                                                        self.block_size)
            if key is not None:
                obstrace.instant("kv.handoff_export", blocks=0,
                                 bytes=len(blob), covered=int(covered),
                                 from_tier=True)
                return key, covered, blob
        return None, 0, None

    def deliver_chain_blob(self, blob, max_bytes=None):
        """Cross-replica handoff delivery (any thread): validate the
        blob's envelope against THIS engine's trunk signature and park
        it in the host tier.  The next request whose context the blob
        covers seats it through the EXISTING restore pipeline
        (``_maybe_begin_restore`` claim → async stage → between-steps
        commit) — no new jitted code, no new write shape.  Returns
        ``(key, covered)``; raises ``WireFormatError`` (foreign,
        garbled, or pool-poisoning header) or ``ConfigError`` (no host
        tier attached — decode-role replicas need
        ``kv_host_bytes > 0``)."""
        if self._host_tier is None:
            raise ConfigError(
                "handoff delivery needs the host tier: run the decode "
                "replica with kv_host_bytes > 0")
        header = peek_chain_header(blob, self._trunk_sig, max_bytes)
        key = tuple(int(t) for t in header.get("tokens", ()))
        covered = int(header.get("covered", 0))
        # a header that lies about its coverage could wedge receivers in
        # eternal claim-defer (covered > pool) or seat garbage past the
        # key — reject it before it touches the tier
        if not key or covered != len(key) or covered > self.max_len:
            raise WireFormatError(
                f"handoff blob declares covered={covered} over a "
                f"{len(key)}-token key (max_len {self.max_len}); "
                "refusing to pool it")
        self._host_tier.put(key, covered, blob)
        self.metrics.set_host_tier_bytes(self._host_tier.bytes)
        return key, covered

    def _maybe_begin_restore(self, full):
        """Probe the host tier for a spilled coverage of ``full`` after
        the resident prefix index missed.  On a worthwhile hit whose
        restore the analytic model predicts to beat recompute: claim
        fresh blocks (``claim_pending``) and submit the staging job
        (deserialize + per-block ``device_put``) to the tier's transfer
        thread, then return ``RestorePendingError`` — the batcher defers
        the request exactly like a pool-dry one, and its retry after
        ``poll_restores`` commits seats an ordinary resident hit.
        Returns None to route as a plain miss (no tier, no coverage, or
        the model says recompute)."""
        tier = self._host_tier
        if tier is None:
            return None
        full = np.asarray(full, np.int32)
        key, covered, blob = tier.lookup(full, self.block_size)
        if key is None \
                or not self.cached_seat_worthwhile(covered, full.size):
            return None
        if key in self._pending_restores:
            return RestorePendingError(
                f"host-tier restore of {covered} position(s) already "
                "in flight")
        faster, restore_ms, recompute_ms = \
            self._restore_predicted_faster(covered)
        obstrace.instant("kv.restore_route", covered=int(covered),
                         restore_ms=round(restore_ms, 4),
                         recompute_ms=round(recompute_ms, 4),
                         restore=faster)
        if not faster:
            return None
        try:
            self._paged.claim_pending(key, covered)
        except InsufficientBlocksError as e:
            return e        # defer without a marker: the pool must
            #                 drain before the claim can even be staged
        names = self._cache_leaf_names
        treedef = self._cache_treedef
        sig = self._trunk_sig

        def _stage(blob=blob):
            # transfer-thread body: deserialize + rebuild one chunk
            # pytree per block (the cache STRUCTURE was frozen at
            # construction — the live donated cache is never touched
            # here) and device_put each; the worker thread _jit_writes
            # them into the claimed blocks between steps
            _toks, cov, arrays = restore_chain(blob, sig)
            named = dict(arrays)
            n_blocks = int(named[names[0]].shape[0]) if names else 0
            chunks = []
            for j in range(n_blocks):
                chunk = jax.tree_util.tree_unflatten(
                    treedef, [named[n][j] for n in names])
                chunks.append(jax.device_put(chunk))
            return cov, chunks

        self._pending_restores[key] = (self._epoch, time.perf_counter())
        tier.submit(key, _stage)
        return RestorePendingError(
            f"host-tier restore of {covered} position(s) started")

    def poll_restores(self, timeout=0.0):
        """Land completed host-tier restores, strictly BETWEEN steps
        (the batcher worker calls this at the top of its loop): write
        each staged chunk into its claimed block (``_jit_write`` — the
        one compiled write shape, zero new traces), publish the chain
        into the prefix index (``commit_pending``), and drop the blob.
        Epoch-guarded: a job submitted before a ``reset()`` is dropped —
        its claim died with the replaced paged state and its blob stays
        resident for the next probe.  A failed job releases its claim
        and forgets the blob (recompute serves the prefix instead).
        Returns the number of restores committed."""
        tier = self._host_tier
        if tier is None or not self._pending_restores:
            return 0
        landed = 0
        while self._pending_restores:
            job = tier.poll(timeout=timeout if not landed else 0.0)
            if job is None:
                break
            key, result = job
            info = self._pending_restores.pop(key, None)
            if info is None:
                continue        # marker cleared by a reset
            epoch, t0 = info
            if epoch != self._epoch:
                obstrace.instant("kv.restore_stale")
                continue
            from paddle_tpu.data.prefetch import _Failure
            chain = list(self._paged._pending.get(key, ()))
            if isinstance(result, _Failure):
                self._paged.release_pending(key)
                tier.pop(key)   # a blob that failed to stage must not
                #                 retry forever
                logger.warning(
                    "%s: host-tier restore failed (prefix falls back to "
                    "recompute): %s: %s", self.name,
                    type(result.exc).__name__, result.exc)
                continue
            covered, chunks = result
            if len(chunks) != len(chain):
                self._paged.release_pending(key)
                tier.pop(key)
                logger.warning(
                    "%s: host-tier restore staged %d block(s) for a "
                    "%d-block claim; dropped", self.name, len(chunks),
                    len(chain))
                continue
            for bid, chunk in zip(chain, chunks):
                self._cache = self._jit_write(self._cache, chunk,
                                              np.int32(bid))
            self._paged.commit_pending(key, covered)
            ent = tier.pop(key)
            self.metrics.observe_kv_restore(
                len(ent[1]) if ent else 0, time.perf_counter() - t0)
            self.metrics.set_host_tier_bytes(tier.bytes)
            obstrace.instant("kv.restore_commit", blocks=len(chain),
                             covered=int(covered))
            landed += 1
        return landed

    def seat_prefilled(self, fulls):
        """THE seat-prefix helper (one definition, four callers:
        ``Supervisor.reprefill`` slot recovery, the batcher's
        continuation-``replay`` leg, paged prefix-cache admission, and
        pool-pressure re-seating).  For each 1-D ``full`` context array,
        reconstruct a slot holding K/V for its prefix with the following
        token armed, WITHOUT re-emitting anything:

        1. paged + prefix cache: a resident chain seats by REFERENCE
           (``seat_cached`` — zero prefill compute);
        2. otherwise re-PREFILL the longest ladder-covered prefix
           ``full[:min(len(full) - 1, ladder_top)]`` — same-bucket items
           as ONE engine batch — and seat it (``admit``).

        Either way the remainder returns as the teacher-forced
        ``replay_feed`` the batcher drains through the shared step with
        re-derived emissions swallowed; greedy decode being
        deterministic, the slot ends byte-for-byte at its target state.
        Returns a list aligned with ``fulls``: ``(slot, replay_feed)``
        per seated item, or the exception that failed it
        (``InsufficientBlocksError`` means "defer and retry", not
        "fail").

        CHUNKED mode (prefill_chunk > 0) replaces leg 2 entirely: there
        is no ladder, so the whole uncovered context returns as the
        feed and the batcher drains it K lanes per step through the ONE
        unified executable — supervisor recovery and continuation
        replay ride chunks instead of one teacher-forced token per
        step."""
        if self.prefill_chunk:
            return self._seat_prefilled_chunked(fulls)
        top = self.prefill_buckets[-1]
        results = [None] * len(fulls)
        prep = []
        for i, full in enumerate(fulls):
            full = np.asarray(full, np.int32)
            if self.kv_layout == "paged":
                covered, chain = self._paged.lookup_prefix(full)
                if covered and self.cached_seat_worthwhile(covered,
                                                           full.size):
                    try:
                        results[i] = self.seat_cached(full, covered, chain)
                    except Exception as e:    # noqa: BLE001 — isolate
                        results[i] = e        # to this item
                    continue
                # resident miss: a spilled twin may be one host-link
                # stream away — defer behind the async restore when the
                # analytic model says that beats re-prefilling
                pending = self._maybe_begin_restore(full)
                if pending is not None:
                    results[i] = pending
                    continue
            pre = min(full.size - 1, top)
            if self.kv_layout == "paged" and not self.can_admit(pre + 1):
                # pool-dry fast path: admit() below would raise this
                # AFTER the prefill ran; gate here so every defer-and-
                # retry cycle costs zero device work while the pool
                # stays dry (admit stays the authoritative backstop)
                results[i] = InsufficientBlocksError(
                    f"pool cannot hold {pre + 1} positions yet")
                continue
            prep.append((i, full, pre))
        groups = {}
        for item in prep:
            groups.setdefault(self.prefill_bucket_for(item[2]),
                              []).append(item)
        for bucket, items in sorted(groups.items()):
            prompts = np.zeros((len(items), bucket), np.int32)
            lengths = np.zeros((len(items),), np.int32)
            for j, (_i, full, pre) in enumerate(items):
                prompts[j, :pre] = full[:pre]
                lengths[j] = pre
            try:
                # reconstruction prefill (recovery / continuation /
                # pool re-seat): one standalone span per bucket batch
                with obstrace.span("gen.prefill", root=False,
                                   bucket=int(bucket), n=len(items)):
                    _first, rows = self.prefill(prompts, lengths)
            except Exception as e:      # noqa: BLE001 — crosses to the
                for i, _full, _pre in items:    # caller per item
                    results[i] = e
                continue
            for j, (i, full, pre) in enumerate(items):
                try:
                    # arm with the recorded stream's next token (inside
                    # the prompt the model's own prediction is
                    # irrelevant; past it, identical)
                    slot = self.admit(np.int32(full[pre]), rows[j],
                                      np.int32(pre), tokens=full[:pre])
                except Exception as e:  # noqa: BLE001
                    results[i] = e
                    continue
                results[i] = (slot, [int(t) for t in full[pre + 1:]])
        return results

    def _seat_prefilled_chunked(self, fulls):
        """``seat_prefilled`` for the unified chunked engine: resident
        prefixes still seat by REFERENCE (paged prefix cache); every
        other context seats via ``seat_chunked`` with the WHOLE context
        as the feed.  Same per-item isolation / defer-and-retry
        contract."""
        results = [None] * len(fulls)
        for i, full in enumerate(fulls):
            full = np.asarray(full, np.int32)
            if self.kv_layout == "paged":
                covered, chain = self._paged.lookup_prefix(full)
                if covered and self.cached_seat_worthwhile(covered,
                                                           full.size):
                    try:
                        results[i] = self.seat_cached(full, covered,
                                                      chain)
                        self.prefill_positions_total += max(
                            0, int(full.size) - int(covered))
                    except Exception as e:  # noqa: BLE001 — isolate
                        results[i] = e      # to this item
                    continue
                # resident miss: consult the host tier before burning
                # chunk steps on a prefix one restore away
                pending = self._maybe_begin_restore(full)
                if pending is not None:
                    results[i] = pending
                    continue
                if not self.can_admit(full.size + 1):
                    # pool-dry fast path: defer before burning any work
                    # (growth preemption covers transient shortfalls,
                    # but a context the pool can't plausibly hold yet
                    # should wait, not thrash victims)
                    results[i] = InsufficientBlocksError(
                        f"pool cannot hold {int(full.size) + 1} "
                        "positions yet")
                    continue
            try:
                results[i] = self.seat_chunked(full)
                self.prefill_positions_total += int(full.size)
            except Exception as e:  # noqa: BLE001 — per-item isolation
                results[i] = e
        return results

    def cached_seat_worthwhile(self, covered, size):
        """Seat through the prefix cache only when the resident coverage
        saves at least half the ladder-covered prefill: the uncovered
        remainder teacher-forces ONE DECODE STEP PER TOKEN, so a short
        shared preamble on a long prompt would cost more steps (and
        worse TTFT) than the single whole-prompt prefill it avoids —
        route those as ordinary misses instead.  CHUNKED mode has no
        ladder and the remainder rides K-lane chunks, so ANY resident
        coverage strictly shrinks the feed: always worthwhile."""
        if self.prefill_chunk:
            return covered > 0
        return covered * 2 >= min(int(size) - 1, self.prefill_buckets[-1])

    def prefix_lookup(self, prompt):
        """``(covered_positions, chain)`` of the longest cached block-
        aligned prefix of ``prompt`` — ``(0, [])`` on a miss or on the
        slab layout.  Read-only (an LRU touch); seating takes the
        references."""
        if self.kv_layout != "paged":
            return 0, []
        return self._paged.lookup_prefix(np.asarray(prompt))

    def can_admit(self, n_positions):
        """Paged admission gate: could the pool produce blocks covering
        ``n_positions`` right now (free list + evictable prefix-index
        entries)?  Always True on the slab layout (the slab reserves per
        slot up front)."""
        if self.kv_layout != "paged":
            return True
        return self._paged.can_admit(int(n_positions))

    def prepare_step(self):
        """Paged layout: make every active slot's CURRENT write position
        exclusive before the step — grow chains into fresh blocks, and
        copy-on-write fork blocks still shared with the prefix index or
        another slot (``cow_forks_total``).  Under pool exhaustion,
        preempt victim slots youngest-first (``evictions{reason=
        "pool_exhausted"}``) and return their ids — the batcher re-seats
        those requests through ``seat_prefilled`` once space frees, so
        their streams continue bit-identically.  Slab layout: no-op."""
        if self.kv_layout != "paged":
            return []
        victims = []
        free_set = set(self._free)
        bs = self.block_size
        for slot in range(self.num_slots):
            if slot in free_set or slot in victims:
                continue
            pos = int(self._pos[slot])
            # chunked mode writes a SPAN this step (lane 0 .. lane
            # _len-1): provision every touched block, in order, each
            # CoW executed immediately so a mid-span exhaustion can
            # never orphan a planned fork
            n = int(self._len[slot]) if self.prefill_chunk else 1
            for j in range(pos // bs, (pos + n - 1) // bs + 1):
                p = pos if j == pos // bs else j * bs
                while True:
                    try:
                        plan = self._paged.write_plan(slot, p)
                    except InsufficientBlocksError:
                        v = self._paged.victim(
                            exclude=set(victims) | {slot})
                        if v is None:
                            raise     # one lone request outgrew the pool
                            #           — validate_request bounds this;
                            #           backstop
                        obstrace.instant("kv.pool_exhausted_preempt",
                                         slot=v)
                        self.evict(v, "pool_exhausted")
                        victims.append(v)
                        continue
                    break
                if plan is not None and plan[0] == "cow":
                    _tag, _j, src, dst = plan
                    self._cache = self._jit_copy(self._cache,
                                                 np.int32(src),
                                                 np.int32(dst))
                    obstrace.instant("kv.cow_fork", slot=slot,
                                     src=int(src), dst=int(dst))
                    self.metrics.observe_cow_fork()
        return victims

    def evict(self, slot, reason):
        """Free a slot (between steps).  Slab: the cache row is left
        as-is — the next admission overwrites it wholesale.  Paged: the
        slot's block references release (shared blocks stay resident for
        their other sharers / the prefix index)."""
        if self.kv_layout == "paged":
            self._paged.evict(slot)
        self._arm(slot, 0, 0)
        self._draft_seed(slot, [])
        self._free.append(slot)
        self.metrics.evict_slot(reason)

    def step(self):
        """Advance EVERY slot one position; returns the next token per
        slot ([num_slots] np.int32).  Free slots compute too (fixed-shape
        slab — that is the cost model) but their output is garbage the
        caller ignores and their cache rows are overwritten at admission.
        Callers then bump their active slots via ``advance``.

        Epoch-guarded: inputs are snapshotted up front and the result is
        only committed if no ``reset()`` happened meanwhile — so a
        watchdog-abandoned step that finishes late consumes its own
        (already orphaned) cache buffer and then discards itself,
        instead of poisoning the rebuilt slab."""
        epoch = self._epoch
        params, cache = self.params, self._cache
        tokens, pos = self._tokens.copy(), self._pos.copy()
        lens = self._len.copy() if self.prefill_chunk else None
        # verify spans armed for THIS step (speculative mode); popped
        # with the snapshot so an eviction racing the step can never
        # resurrect a stale acceptance
        spec_armed = {}
        if self._draft is not None:
            spec_armed, self._spec_armed = self._spec_armed, {}
        # the fault point sits at the device-step boundary: a hang here
        # models a wedged device step for the watchdog to catch
        faults.hit("serving.decode_step")
        t0 = time.perf_counter()
        if self.prefill_chunk and self.kv_layout == "paged":
            nxt, cache = self._jit_step(params, cache, tokens, pos, lens,
                                        self._paged.tables.copy())
        elif self.prefill_chunk:
            nxt, cache = self._jit_step(params, cache, tokens, pos, lens)
        elif self.kv_layout == "paged":
            # block tables ride as DATA (snapshotted, like tokens/pos):
            # table churn between steps never retraces
            nxt, cache = self._jit_step(params, cache, tokens, pos,
                                        self._paged.tables.copy())
        else:
            nxt, cache = self._jit_step(params, cache, tokens, pos)
        nxt = np.asarray(nxt)
        with self._epoch_lock:
            if epoch != self._epoch:
                raise RuntimeError(
                    f"{self.name}: engine was reset mid-step; stale step "
                    "result discarded")
            self._cache = cache
        # teacher-forced lanes this step fed beyond the per-slot token
        # (the chunked-prefill occupancy surface)
        chunk_lanes = int(lens.sum() - self.num_slots) if lens is not None \
            else 0
        kw = {}
        if self._draft is not None:
            # speculating step output is EVERY lane's argmax [S, K]:
            # row[i] is the target's greedy pick after lane i.
            # Acceptance per armed slot: lanes 1..k_eff held drafts
            # d_1..d_k; the matched prefix is the run of d_{i+1} ==
            # row[i], and row[j] at the first mismatch is the target's
            # OWN next token — the accepted run row[:j+1] is exactly
            # what sequential greedy decode would have emitted, which is
            # the whole bit-identity argument.  Non-speculating rows
            # reduce to their last fed lane, same as a plain engine.
            rows = nxt
            nxt = rows[np.arange(self.num_slots), lens - 1]
            accepted = drafted = 0
            for slot, k_eff in spec_armed.items():
                row, want = rows[slot], tokens[slot, 1:1 + k_eff]
                j = 0
                while j < k_eff and int(row[j]) == int(want[j]):
                    j += 1
                self._spec_result[slot] = [int(t) for t in row[:j + 1]]
                accepted += j
                drafted += k_eff
            # draft lanes are speculation, not prompt ingestion: keep
            # them out of the prefill-occupancy surface
            chunk_lanes -= drafted
            # kwargs passed ONLY in spec mode: test spies subclassing
            # observe_decode_step with the old signature stay valid on
            # non-speculating engines
            kw = dict(accepted_tokens=accepted, drafted_tokens=drafted,
                      spec_slots=len(spec_armed))
        self.metrics.observe_decode_step(self.num_active, self.num_slots,
                                         time.perf_counter() - t0,
                                         prefill_lanes=chunk_lanes, **kw)
        if self.kv_layout == "paged":
            self.metrics.set_kv_pool(self._paged.pool.num_free,
                                     self._paged.pool.num_allocatable)
        return nxt

    def advance(self, slot, token, consumed=1):
        """Record the token fed at the next step for ``slot``, advanced
        past the ``consumed`` lanes the last step processed (1 = plain
        decode; a chunked step advances by its lane count — the
        per-slot variable advance)."""
        if self._draft is not None:
            # every committed token re-feeds the draft cache (matched
            # drafts rewrite identical K/V; a mismatch feeds the
            # corrected token over the stale rollout write) — lanes
            # 1..consumed-1 are read BEFORE lane 0 is overwritten
            self._d_feed[slot].extend(
                [int(t) for t in self._tokens[slot, 1:consumed]]
                + [int(token)])
        if self.prefill_chunk:
            self._tokens[slot, 0] = token
            self._len[slot] = 1
        else:
            self._tokens[slot] = token
        self._pos[slot] += consumed
        if self._draft is not None and self._paged is not None:
            # paged rollback (kv_pool.truncate): release blocks the
            # verify span provisioned past the committed stream —
            # keeping the block the next write lands in
            self._paged.truncate(slot, int(self._pos[slot]) + 1)

    def reset(self):
        """Drop all slot state and re-zero the cache slab (the batch-
        failure isolation path: a failed step must not leak a poisoned
        slab into the next batch).  The compiled step/admit/prefill
        executables stay jit-cached — a rebuild costs zero new traces —
        and the epoch bump orphans any still-running stale step."""
        with self._epoch_lock:
            self._epoch += 1
            if self.kv_layout == "paged":
                # fresh pool + allocator + (empty) prefix index: the
                # blocks' contents are gone, so every cached chain is
                # invalid — recovery re-seats through seat_prefilled,
                # which misses and re-prefills.  REPLACE the state (a
                # watchdog-abandoned stale step may still be reading the
                # old tables array).
                old = self._paged
                self._paged = PagedKVState(
                    self.num_slots, old.pool.num_blocks, self.block_size,
                    self.max_len, prefix_cache=old.index is not None,
                    on_evict=self._spill_chain
                    if self._host_tier is not None else None)
                # in-flight restore claims died with the old state;
                # poll_restores drops their jobs at the epoch check, and
                # the blobs stay in the tier — recovery re-seats can
                # restore-hit the same spilled prefixes
                self._pending_restores.clear()
                # _place_cache: a sharded engine's rebuilt pool must come
                # back with the same mesh placement or the (still-cached)
                # compiled step would see new shardings and recompile
                self._cache = self._place_cache(
                    self._transformer.init_lm_cache_paged(
                        self.params, old.pool.num_blocks, self.block_size,
                        max_len=self.max_len, kv_dtype=self.kv_dtype,
                        num_heads=self.num_heads))
            else:
                self._cache = self._place_cache(
                    self._transformer.init_lm_cache(
                        self.params, self.num_slots, self.max_len,
                        kv_dtype=self.kv_dtype, num_heads=self.num_heads))
        self._tokens[:] = 0
        self._pos[:] = 0
        if self.prefill_chunk:
            self._len[:] = 1
        self._free = list(range(self.num_slots))[::-1]
        if self._draft is not None:
            # BOTH caches rebuild: recovery re-seats every stream and
            # its context re-feeds the draft through _draft_seed
            self._draft.reset()
            self._d_feed = [[] for _ in range(self.num_slots)]
            self._d_pos[:] = 0
            self._d_last[:] = 0
            self._spec_armed.clear()
            self._spec_result.clear()

    # ------------------------------------------------------------ warm-up

    def warmup(self):
        """Compile + execute the slab step, the admission write, and every
        prefill ladder engine before traffic, asserting the trace
        discipline: the step's Python body traces exactly ONCE here and
        never again in steady state (admission/eviction are host-side, so
        churn cannot retrace by construction — the churn test pins it).
        Idempotent: a second call only warms prefill buckets added since."""
        if not self.prefill_chunk:
            # the legacy ladder: one engine per prompt-length bucket.
            # The chunked engine has NO prefill plane to warm — the one
            # step below is the entire serving hot path.
            for b in self.prefill_buckets:
                self._prefill_engine(b).warmup()
        if self._warm:
            return
        # resolve the kernel path NOW — warm-up is the step's one trace,
        # so this is the selection the compiled step actually took
        # (ops/pallas/decode_attention.py; pallas_decode flag)
        from paddle_tpu.ops.pallas import decode_attention as _dk
        enc = self.params.get("enc") or []
        if enc:
            d = int(_w_shape(self.params["src_emb"])[1])
            dkv = int(_w_shape(enc[0]["attn"]["wk"])[1])
            blk_len = (self.block_size if self.kv_layout == "paged"
                       else self.max_len)
            # covers() sees the PER-CHIP stripe (shards=): a kernel that
            # covers 8 KV heads may not cover the 4-head shard — the
            # resolved path below is what the compiled step actually took
            self.decode_kernels = _dk.covers(
                self.num_heads, d, dkv, blk_len,
                paged=self.kv_layout == "paged",
                chunk=self._kk or 1,
                quant=self.kv_dtype == "int8",
                shards=self.mesh_shards)
            if self.mesh_shards > 1 and not self.decode_kernels \
                    and _dk.covers(self.num_heads, d, dkv, blk_len,
                                   paged=self.kv_layout == "paged",
                                   chunk=self._kk or 1,
                                   quant=self.kv_dtype == "int8"):
                logger.info(
                    "decode[%s]: fused kernel covers the FULL trunk but "
                    "not the per-chip Hkv/%d head stripe -> xla-ref",
                    self.name, self.mesh_shards)
        self.metrics.set_prefill_chunk(self.prefill_chunk)
        self.metrics.set_kv_dtype(self.kv_dtype)
        self.metrics.set_speculate_k(self.speculate_k)
        self.metrics.set_mesh_shards(self.mesh_shards)
        if self._draft is not None:
            # the draft rollout is its own ONE warm-up trace
            self._draft.warmup()
        if self.prefill_chunk:
            if self.kv_layout == "paged":
                if self._host_tier is not None:
                    # host-tier restores land through the block write;
                    # warm it HERE so the first restore commits with
                    # zero new compiles (chunked ingestion itself never
                    # uses it — prompt writes ride the step)
                    chunk = jax.tree_util.tree_map(
                        lambda l: np.zeros(l.shape[1:], l.dtype),
                        self._cache)
                    with expect_traces(lambda: self._write_traces[0], 1,
                                       f"decode[{self.name}]: "
                                       "block-write warm-up"):
                        self._cache = self._jit_write(self._cache, chunk,
                                                      np.int32(0))
                # the CoW fork is the only other device op the chunked
                # paged engine uses (block writes ride the step itself)
                with expect_traces(lambda: self._copy_traces[0], 1,
                                   f"decode[{self.name}]: block-fork "
                                   "warm-up"):
                    self._cache = self._jit_copy(self._cache, np.int32(0),
                                                 np.int32(0))
                with expect_traces(
                        lambda: self.step_trace_count, 1,
                        f"decode[{self.name}]: chunked paged step "
                        "warm-up",
                        hint="the chunked step is not shape-stable"):
                    nxt, self._cache = self._jit_step(
                        self.params, self._cache, self._tokens,
                        self._pos, self._len, self._paged.tables.copy())
                    jax.block_until_ready(nxt)
            else:
                with expect_traces(
                        lambda: self.step_trace_count, 1,
                        f"decode[{self.name}]: chunked slab step "
                        "warm-up",
                        hint="the chunked step is not shape-stable"):
                    nxt, self._cache = self._jit_step(
                        self.params, self._cache, self._tokens,
                        self._pos, self._len)
                    jax.block_until_ready(nxt)
            self._warm = True
            logger.info(
                "decode[%s]: warm (%d slots, max_len %d, kv %s/%s, decode "
                "kernels %s, chunked prefill K=%d budget=%s, "
                "speculate_k=%d, mesh_shards=%d)", self.name,
                self.num_slots, self.max_len, self.kv_layout,
                self.kv_dtype,
                "fused-pallas" if self.decode_kernels else "xla-ref",
                self.prefill_chunk, self.prefill_chunk_budget or "inf",
                self.speculate_k, self.mesh_shards)
            return
        if self.kv_layout == "paged":
            # ONE block-write shape and ONE fork shape serve every
            # bucket/admission/CoW — both warmed (and executed) against
            # the scratch block, whose contents are never attended
            chunk = jax.tree_util.tree_map(
                lambda l: np.zeros(l.shape[1:], l.dtype), self._cache)
            with expect_traces(lambda: self._write_traces[0], 1,
                               f"decode[{self.name}]: block-write "
                               "warm-up"):
                self._cache = self._jit_write(self._cache, chunk,
                                              np.int32(0))
            with expect_traces(lambda: self._copy_traces[0], 1,
                               f"decode[{self.name}]: block-fork "
                               "warm-up"):
                self._cache = self._jit_copy(self._cache, np.int32(0),
                                             np.int32(0))
            with expect_traces(lambda: self.step_trace_count, 1,
                               f"decode[{self.name}]: paged step warm-up",
                               hint="the decode step is not shape-stable"):
                nxt, self._cache = self._jit_step(
                    self.params, self._cache, self._tokens, self._pos,
                    self._paged.tables.copy())
                jax.block_until_ready(nxt)
        else:
            for b in self.prefill_buckets:
                zero_row = jax.tree_util.tree_map(
                    lambda l: np.zeros((b,) + l.shape[2:], l.dtype),
                    self._cache)
                with expect_traces(lambda: self._admit_traces[0], 1,
                                   f"decode[{self.name}]: bucket-{b} "
                                   "admission warm-up"):
                    self._cache = self._jit_admit(self._cache, zero_row,
                                                  np.int32(0))
            with expect_traces(lambda: self.step_trace_count, 1,
                               f"decode[{self.name}]: slab step warm-up",
                               hint="the decode step is not shape-stable"):
                nxt, self._cache = self._jit_step(
                    self.params, self._cache, self._tokens, self._pos)
                jax.block_until_ready(nxt)
        self._warm = True
        logger.info("decode[%s]: warm (%d slots, max_len %d, kv %s/%s, "
                    "decode kernels %s, prefill buckets %s)", self.name,
                    self.num_slots, self.max_len, self.kv_layout,
                    self.kv_dtype,
                    "fused-pallas" if self.decode_kernels else "xla-ref",
                    list(self.prefill_buckets))

    def lower(self, what="step"):
        """``jax.stages.Lowered`` of the slab decode step (default) or of
        one prefill bucket (``what=<bucket int>``) — the ``extras
        ["lower"]`` analytic hook (perf/analytic.py).  ``what="draft"``
        lowers the attached draft trunk's rollout instead.  Offline
        tool: it re-stages the function (one extra trace), like
        ``InferenceEngine.lower``."""
        if what == "draft":
            if self._draft is None:
                raise ConfigError(
                    f"{self.name}: no draft trunk (speculate_k=0)")
            return self._draft.lower()
        if what == "step":
            if self.prefill_chunk and self.kv_layout == "paged":
                return self._jit_step.lower(self.params, self._cache,
                                            self._tokens, self._pos,
                                            self._len,
                                            self._paged.tables)
            if self.prefill_chunk:
                return self._jit_step.lower(self.params, self._cache,
                                            self._tokens, self._pos,
                                            self._len)
            if self.kv_layout == "paged":
                return self._jit_step.lower(self.params, self._cache,
                                            self._tokens, self._pos,
                                            self._paged.tables)
            return self._jit_step.lower(self.params, self._cache,
                                        self._tokens, self._pos)
        return self._prefill_engine(int(what)).lower(
            self._prefill_batch_buckets[-1])

    # ------------------------------------------------------------ validate

    def _validate_ids(self, name, ids):
        """Shared admission check: a non-empty 1-D integer id sequence
        within the vocab.  Returns the array."""
        ids = np.asarray(ids)
        if ids.ndim != 1 or ids.size < 1:
            raise InvalidRequestError(
                f"{name} must be a non-empty 1-D id sequence, got shape "
                f"{ids.shape}")
        if not np.issubdtype(ids.dtype, np.integer):
            raise InvalidRequestError(
                f"{name} must be integer token ids, got {ids.dtype}")
        vocab = _w_shape(self.params["src_emb"])[0]
        if int(ids.min()) < 0 or int(ids.max()) >= vocab:
            raise InvalidRequestError(
                f"{name} ids must be in [0, {vocab}); got "
                f"[{int(ids.min())}, {int(ids.max())}]")
        return ids

    @staticmethod
    def _parse_max_tokens(max_tokens):
        try:
            max_tokens = int(max_tokens)
        except (TypeError, ValueError):
            raise InvalidRequestError(
                f"max_tokens must be an int, got {max_tokens!r}") from None
        if max_tokens < 1:
            raise InvalidRequestError(f"max_tokens={max_tokens} must be "
                                      ">= 1")
        return max_tokens

    def validate_request(self, prompt, max_tokens):
        """Admission-control checks, raised BEFORE the queue.  The
        chunked engine has no ladder, so only ``max_len`` caps the
        prompt (chunks bound per-STEP work instead)."""
        prompt = self._validate_ids("prompt", prompt)
        if not self.prefill_chunk \
                and prompt.size > self.prefill_buckets[-1]:
            raise InvalidRequestError(
                f"prompt length {prompt.size} exceeds the prefill ladder "
                f"top {self.prefill_buckets[-1]}")
        max_tokens = self._parse_max_tokens(max_tokens)
        if prompt.size + max_tokens > self.max_len:
            raise InvalidRequestError(
                f"prompt ({prompt.size}) + max_tokens ({max_tokens}) "
                f"exceeds the engine max_len ({self.max_len})")
        self._check_pool_fit(prompt.size + max_tokens)
        return prompt.astype(np.int32), max_tokens

    def _check_pool_fit(self, n_positions):
        """Paged: one request must fit the pool ALONE (the runtime
        preemption path can evict every other slot but never this one —
        docs/serving.md §5 pool sizing)."""
        if self.kv_layout != "paged":
            return
        need = self._paged.blocks_for(n_positions)
        if need > self._paged.pool.num_allocatable:
            raise InvalidRequestError(
                f"request needs {need} KV blocks of "
                f"{self.block_size} positions but the pool only holds "
                f"{self._paged.pool.num_allocatable}")

    def validate_continuation(self, prompt, replay, max_tokens):
        """Admission checks for a mid-stream CONTINUATION: ``replay``
        tokens were already delivered to the caller by a previous serving
        of this stream (a router failing over off a dead replica —
        docs/serving.md §7) and must be teacher-forced, never re-emitted.
        Unlike a fresh prompt, the combined context may exceed the
        prefill ladder top — seating re-prefills the longest
        ladder-covered prefix and replays the remainder through the slab
        step (the exact ``Supervisor.reprefill`` contract), so only the
        slab length bounds it: ``len(prompt) + len(replay) + max_tokens
        <= max_len``."""
        prompt = self._validate_ids("prompt", prompt)
        replay = self._validate_ids("replay", replay)
        max_tokens = self._parse_max_tokens(max_tokens)
        if prompt.size + replay.size + max_tokens > self.max_len:
            raise InvalidRequestError(
                f"prompt ({prompt.size}) + replay ({replay.size}) + "
                f"max_tokens ({max_tokens}) exceeds the engine max_len "
                f"({self.max_len})")
        self._check_pool_fit(prompt.size + replay.size + max_tokens)
        return prompt.astype(np.int32), replay.astype(np.int32), max_tokens


class _GenRequest:
    __slots__ = ("prompt", "max_tokens", "eos_id", "future", "deadline",
                 "t_submit", "t_first", "on_token", "tokens", "slot",
                 "abandoned", "recoveries", "replay_feed", "replay_ctx",
                 "started", "admit_covered", "prefix_counted",
                 "trace_ctx", "queue_span", "slot_span")

    def __init__(self, prompt, max_tokens, eos_id, deadline, on_token,
                 replay_ctx=None):
        # tracing (obs/trace.py): the submitting thread's context (the
        # HTTP handler's request span) is captured HERE because the
        # worker thread that seats and decodes this request has no
        # ambient context of its own.  submit() starts queue_span only
        # once the request is actually enqueued (a rejected submit must
        # not leak a forever-active span); it ends at admission pickup.
        # slot_span is the request's slot-LIFETIME span (seat ->
        # eviction, carrying TTFT/recovery/preemption events).
        self.trace_ctx = obstrace.current()
        self.queue_span = obstrace.NULL
        self.slot_span = obstrace.NULL
        self.abandoned = False
        self.recoveries = 0
        self.started = False      # future marked running (a request can
        #                           re-enter admission — pool-deferred —
        #                           but the transition fires once)
        self.replay_feed = []     # recovery replay: recorded tokens still
        #                           to teacher-force through the slab step
        self.replay_ctx = replay_ctx   # continuation context: tokens a
        #                                previous serving of this stream
        #                                already delivered (never re-emitted)
        self.admit_covered = 0    # this admission pass's prefix-cache
        #                           lookup (positions covered), reused by
        #                           routing so the pass looks up once
        self.prefix_counted = False   # hit/miss observed (a pool-
        #                               deferred request re-enters
        #                               admission; the counter must see
        #                               it once)
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.eos_id = eos_id
        self.future = Future()
        self.deadline = deadline          # absolute perf_counter() or None
        self.t_submit = time.perf_counter()
        self.t_first = None
        self.on_token = on_token
        self.tokens = []
        self.slot = None

    @property
    def context(self):
        """Every token the stream holds BEFORE its first new emission:
        the prompt plus (for a continuation) the already-delivered replay
        tokens — what slot recovery must reconstruct."""
        if self.replay_ctx is None:
            return self.prompt
        return np.concatenate([self.prompt, self.replay_ctx])

    def fail(self, exc):
        # end both trace spans (idempotent): a request failed while
        # queued or seated must not leak forever-active spans
        self.queue_span.end()
        self.slot_span.end(reason="failed",
                           error=type(exc).__name__,
                           tokens=len(self.tokens))
        try:
            self.future.set_exception(exc)
        except InvalidStateError:
            pass

    def emit(self, token, name):
        self.tokens.append(int(token))
        if self.t_first is None:
            self.t_first = time.perf_counter()
        if self.on_token is not None:
            try:
                self.on_token(int(token))
            except Exception as e:    # noqa: BLE001 — a client callback
                # must never wedge the decode loop
                logger.warning("%s: on_token callback failed: %s: %s",
                               name, type(e).__name__, e)
                self.on_token = None


class GenerationBatcher:
    """Continuous-batching front for a ``DecodeEngine`` — the generation
    twin of ``Batcher``: bounded queue, futures, deadlines, drain; plus
    streaming (per-token callbacks) and slot scheduling.

    ONE worker thread runs the loop: admit queued requests into free
    slots (prefilling same-bucket prompts together through the ladder),
    run one slab step, deliver each active slot's token, evict finished
    slots.  Admission happens strictly BETWEEN steps, so the compiled
    step never sees a shape change.

    admission="continuous" (the point of this module) refills freed slots
    from the queue between ANY two steps.  admission="gang" only admits
    into an EMPTY slab and runs that gang to completion — the sequential
    whole-batch policy ``lm_generate`` imposes (finished rows burn steps
    until the slowest row is done; arrivals wait for the drain).  Same
    compiled step, same prefill ladder, so ``bench.py serving_generate``'s
    continuous-vs-sequential comparison isolates exactly the scheduling
    policy.
    """

    def __init__(self, engine, queue_size=256, default_deadline_ms=None,
                 default_max_tokens=64, admission="continuous", name=None,
                 supervisor=None):
        self.engine = engine
        self.metrics = engine.metrics
        # resilience.Supervisor (None = PR-5 semantics: a step failure
        # fails the in-flight batch).  With one attached: step failures
        # and watchdog trips REBUILD the slab and re-prefill every
        # in-flight request (streams continue bit-identically), and the
        # circuit breaker sheds admissions after repeated failures.
        self.supervisor = supervisor
        self.default_deadline_s = (float(default_deadline_ms) / 1e3
                                   if default_deadline_ms else None)
        self.default_max_tokens = int(default_max_tokens)
        if int(queue_size) < 1:
            raise ValueError("queue_size must be >= 1")
        if admission not in ("continuous", "gang"):
            raise ValueError(f"admission={admission!r} (supported: "
                             "'continuous', 'gang')")
        self._gang = admission == "gang"
        self._q = queue.Queue(maxsize=int(queue_size))
        # cross-replica KV exports (serving/transfer.py): HTTP handlers
        # queue (tokens, result_box, done_event) here and the worker
        # serves them strictly between steps — the gather must read the
        # committed cache, which belongs to the worker thread
        self._export_q = queue.Queue()
        self._depth_fn = self._q.qsize
        self.metrics.queue_depth_fns.append(self._depth_fn)
        self._closed = threading.Event()
        self._drain = True
        self._admit_lock = threading.Lock()
        self._by_slot = {}          # slot -> _GenRequest
        self._abandoned = set()     # futures flagged mid-prefill (before
        #                             their request reached a slot)
        # paged-layout overflow lanes (both worker-thread-only):
        # _waiting: popped requests the pool cannot seat yet (retried
        # ahead of the queue); _preempted: requests whose slot was
        # evicted under pool pressure (reason="pool_exhausted") — they
        # hold delivered tokens and re-seat through seat_prefilled, so
        # their streams continue bit-identically
        self._waiting = collections.deque()
        self._preempted = []
        self.name = name or f"gen_batcher[{engine.name}]"
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name)
        self._thread.start()

    # ------------------------------------------------------------ submit

    def submit(self, prompt, max_tokens=None, eos_id=None, deadline_ms=None,
               on_token=None, replay=None):
        """Admit one generation request; returns a Future resolving to
        ``{"tokens": [ids...], "finish_reason": "eos"|"length",
        "ttft_ms": float}``.

        prompt: 1-D int token ids (<= the prefill ladder top);
        max_tokens: emission cap (default: the batcher default), with
        ``len(prompt) + max_tokens <= engine.max_len``; eos_id: stop
        token override (None = the engine default); on_token: optional
        callable invoked per emitted token from the engine thread (the
        streaming hook — exceptions are logged, never fatal).

        replay: mid-stream CONTINUATION — tokens a previous serving of
        this stream already delivered (a router failing over off a dead
        replica, docs/serving.md §7).  Seating re-prefills the longest
        ladder-covered prefix of ``prompt + replay`` and teacher-forces
        the remainder through the slab step with re-derived emissions
        swallowed (``Supervisor.reprefill`` semantics), so the result's
        ``tokens`` are ONLY the new emissions and — greedy decode being
        deterministic — the concatenated stream is bit-identical to the
        uninterrupted one.  ``max_tokens`` counts new emissions;
        ``len(prompt) + len(replay) + max_tokens <= engine.max_len``
        (the ladder top does NOT cap the combined context).

        Raises synchronously: ``InvalidRequestError``,
        ``OverloadedError`` (queue full), ``ShutdownError`` (draining),
        ``BreakerOpenError`` (circuit breaker shedding; carries
        ``retry_after_s``).
        """
        # fault point FIRST: an injected submit failure provably mutated
        # nothing, so retry_transient's idempotence guarantee holds
        faults.hit("batcher.submit")
        if self._closed.is_set():
            self.metrics.reject("shutdown")
            raise ShutdownError(f"{self.name} is draining; submit rejected")
        try:
            if replay is None:
                prompt, max_tokens = self.engine.validate_request(
                    prompt, max_tokens if max_tokens is not None
                    else self.default_max_tokens)
            else:
                prompt, replay, max_tokens = \
                    self.engine.validate_continuation(
                        prompt, replay,
                        max_tokens if max_tokens is not None
                        else self.default_max_tokens)
        except InvalidRequestError:
            self.metrics.reject("invalid")
            raise
        # breaker AFTER validation: a malformed request must not burn the
        # half-open probe slot (it would never reach a step to resolve it)
        if self.supervisor is not None:
            ok, retry_after = self.supervisor.breaker.admit()
            if not ok:
                self.metrics.reject("breaker")
                self._snap_breaker()
                raise BreakerOpenError(
                    f"{self.name}: circuit breaker open (engine recently "
                    f"failed repeatedly); retry in {retry_after:.2f}s",
                    retry_after_s=retry_after)
        dl_s = (float(deadline_ms) / 1e3 if deadline_ms
                else self.default_deadline_s)
        req = _GenRequest(prompt, max_tokens,
                          self.engine.eos_id if eos_id is None else eos_id,
                          time.perf_counter() + dl_s if dl_s else None,
                          on_token, replay_ctx=replay)
        # start the queue-wait span before the enqueue (the worker may
        # pull the request the instant it lands); the rejection paths
        # below end it so a refused submit leaks nothing
        # root=False: a direct (non-HTTP) submit has no request span,
        # and infrastructure spans must not pollute slowest()
        req.queue_span = obstrace.start_span("gen.queue_wait",
                                             ctx=req.trace_ctx,
                                             root=False)
        with self._admit_lock:
            if self._closed.is_set():   # close() raced the check above
                req.queue_span.end()
                self.metrics.reject("shutdown")
                if self.supervisor is not None:     # the request never
                    self.supervisor.breaker.release_probe()   # ran: hand
                #                                     the probe slot back
                raise ShutdownError(
                    f"{self.name} is draining; submit rejected")
            try:
                self._q.put_nowait(req)
            except queue.Full:
                req.queue_span.end()
                self.metrics.reject("overload")
                if self.supervisor is not None:
                    self.supervisor.breaker.release_probe()
                raise OverloadedError(
                    f"{self.name}: queue full ({self._q.maxsize} waiting)") \
                    from None
        self.metrics.accepted()
        return req.future

    def generate(self, prompt, timeout=None, **kw):
        """submit() + block for the result (the HTTP handler's path)."""
        return self.submit(prompt, **kw).result(timeout)

    def export_chain(self, tokens, timeout=5.0):
        """Serialize the longest resident KV coverage of ``tokens`` for
        a cross-replica handoff (the ``/v1/kv/export`` route's path).
        The gather reads the committed cache — worker-thread state — so
        the request queues and the worker serves it strictly between
        steps (the loop's idle poll is 50ms, bounding the wait).
        Returns ``(key, covered, blob)``, or ``(None, 0, None)`` on no
        coverage, a closed batcher, or timeout."""
        box = [None, 0, None]
        done = threading.Event()
        self._export_q.put((tokens, box, done))
        if not done.wait(timeout):
            return None, 0, None
        return box[0], box[1], box[2]

    def _serve_exports(self):
        """Worker thread, strictly between steps: drain queued
        cross-replica export requests.  An export failure resolves THAT
        request empty (the peer falls back to recompute) and never
        touches the serving loop."""
        while True:
            try:
                tokens, box, done = self._export_q.get_nowait()
            except queue.Empty:
                return
            try:
                box[0], box[1], box[2] = self.engine.export_chain(tokens)
            except Exception as e:      # noqa: BLE001 — isolate to this
                # export; the requester serves a miss (recompute)
                logger.warning("%s: kv export failed: %s: %s",
                               self.name, type(e).__name__, e)
            done.set()

    def abandon(self, future):
        """The caller behind ``future`` is gone (e.g. the streaming HTTP
        client disconnected): stop spending decode steps on it.  A still-
        queued request is cancelled outright; a slotted one is flagged
        and the worker evicts it at the next token boundary instead of
        decoding to max_tokens.  No-op if it already finished."""
        if future.done() or future.cancel():
            return          # finished, or still queued (admission drops
            #                 cancelled work)
        for req in list(self._by_slot.values()):
            if req.future is future:
                req.abandoned = True
                return
        # running but not slotted: it is inside the prefill window —
        # admission checks this set before seating it
        self._abandoned.add(future)

    # ------------------------------------------------------------ worker

    def _pull(self, block):
        if self._waiting:               # pool-deferred requests go first
            return self._waiting.popleft()
        try:
            req = self._q.get(timeout=0.05) if block else \
                self._q.get_nowait()
        except queue.Empty:
            return None
        # the queue wait ends at pickup (idempotent: a pool-deferred
        # request re-enters admission but its wait ended the first time)
        req.queue_span.end()
        return req

    def _finish(self, req, reason):
        """Evict a slotted request and resolve its future."""
        self.engine.evict(req.slot, reason)
        del self._by_slot[req.slot]
        req.slot = None
        self._resolve(req, reason)

    def _resolve(self, req, reason):
        """Resolve a finished request's future — the ONE place the
        response shape is built (slotted finishes and prefill-time
        finishes — max_tokens==1 / immediate eos / abandoned — all land
        here)."""
        self._abandoned.discard(req.future)     # a late abandon() of a
        #                                         finished future is inert
        ttft = (req.t_first - req.t_submit) if req.t_first else 0.0
        # the slot-lifetime span ends with the request, carrying the
        # eviction reason next to TTFT (NULL no-op for requests that
        # finished at prefill and never held a slot)
        req.slot_span.end(reason=reason, tokens=len(req.tokens),
                          ttft_ms=round(ttft * 1e3, 3))
        self.metrics.observe_response(time.perf_counter() - req.t_submit)
        try:
            req.future.set_result({
                "tokens": list(req.tokens),
                "finish_reason": reason,
                "ttft_ms": round(ttft * 1e3, 3),
            })
        except InvalidStateError:
            pass

    def _flag_abandoned(self, req):
        """Fold a mid-prefill ``abandon()`` into the request's flag."""
        if req.future in self._abandoned:
            self._abandoned.discard(req.future)
            req.abandoned = True
        return req.abandoned

    def _admit_from_queue(self, block):
        """Fill free slots from the queue; same-length-bucket prompts
        prefill as ONE engine batch.  Runs strictly between steps.

        Fresh prompts prefill WHOLE and their first emission is
        delivered at admission.  Everything that must be RECONSTRUCTED
        instead — continuations (``replay_ctx``), fresh prompts whose
        prefix is resident in the paged prefix cache, and pool-preempted
        requests — seats through ``engine.seat_prefilled`` (the one
        seat-prefix helper, shared with ``Supervisor.reprefill``):
        teacher-forced remainder, re-derived emissions swallowed, so
        every stream is bit-identical to an uninterrupted one.  On the
        paged layout, requests the pool cannot hold yet are DEFERRED
        (``_waiting`` / ``_preempted``), never failed."""
        if self._gang and self._by_slot:
            return          # whole-batch policy: drain before refilling
        self._reseat_preempted()
        block = block and not self._preempted
        picked = []
        kv_budget = None
        if self.engine.kv_layout == "paged":
            kv_budget = [self.engine._paged.pool.num_free]
        stashed = []
        while self.engine.free_slots > len(picked):
            req = self._pull(block and not picked)
            if req is None:
                break
            block = False
            now = time.perf_counter()
            if req.deadline is not None and now > req.deadline:
                self.metrics.reject("deadline")
                req.fail(DeadlineExceededError(
                    f"deadline exceeded after "
                    f"{(now - req.t_submit) * 1e3:.1f}ms in queue"))
                continue
            if not req.started:
                if not req.future.set_running_or_notify_cancel():
                    continue        # client cancelled while queued
                req.started = True
            covered = 0
            if kv_budget is not None and req.replay_ctx is None:
                covered = self.engine.prefix_lookup(req.prompt)[0]
                if not self.engine.cached_seat_worthwhile(
                        covered, req.prompt.size):
                    covered = 0    # short preamble: route (and budget)
                    #                it as an ordinary whole-prompt miss
                if not covered:
                    # paged fresh miss: it will claim private blocks for
                    # its whole prompt — defer it while the pool (free
                    # blocks minus what this admission round already
                    # earmarked) cannot hold them, instead of prefilling
                    # just to fail
                    need = self.engine._paged.blocks_for(
                        req.prompt.size + 1)
                    if need > kv_budget[0] \
                            and not self.engine.can_admit(
                                req.prompt.size + 1):
                        stashed.append(req)
                        continue
                    kv_budget[0] -= need
            req.admit_covered = covered
            picked.append(req)
        self._waiting.extend(stashed)
        if not picked:
            return
        # route: fresh misses prefill whole (emit at admission); fresh
        # prefix-cache hits and continuations reconstruct via
        # seat_prefilled (nothing re-emitted).  The CHUNKED engine has
        # no prefill plane at all: EVERY request seats through
        # seat_prefilled and its context drains through the unified
        # step as K-lane chunks (first emission at the last chunk).
        fresh, recon = [], []
        for req in picked:
            if self.engine.chunked:
                if self.engine.kv_layout == "paged" \
                        and req.replay_ctx is None \
                        and not req.prefix_counted:
                    req.prefix_counted = True
                    self.metrics.observe_prefix_cache(
                        hit=req.admit_covered > 0)
                recon.append(req)
                continue
            if req.replay_ctx is not None:
                recon.append(req)
                continue
            if self.engine.kv_layout == "paged":
                # the budget-gate loop above already did this request's
                # prefix lookup this pass; seat_prefilled re-looks-up at
                # seating time (the pool may shift as items seat), so
                # that one stays the authoritative reference-taker
                covered = req.admit_covered
                if not req.prefix_counted:
                    req.prefix_counted = True
                    self.metrics.observe_prefix_cache(hit=covered > 0)
                if covered:
                    recon.append(req)
                    continue
            fresh.append(req)
        self._seat_reconstructed(recon)
        groups = {}
        for req in fresh:
            b = self.engine.prefill_bucket_for(req.prompt.size)
            groups.setdefault(b, []).append(req)
        for bucket, reqs in sorted(groups.items()):
            prompts = np.zeros((len(reqs), bucket), np.int32)
            lengths = np.zeros((len(reqs),), np.int32)
            for i, req in enumerate(reqs):
                prompts[i, :req.prompt.size] = req.prompt
                lengths[i] = req.prompt.size
            try:
                # one span per admission prefill batch, parented to the
                # FIRST rider's trace (a batch serves several requests;
                # co-riders see the bucket on their slot span instead)
                with obstrace.span("gen.prefill", ctx=reqs[0].trace_ctx,
                                   root=False, bucket=int(bucket),
                                   n=len(reqs)):
                    first, rows = self.engine.prefill(prompts, lengths)
            except Exception as e:    # noqa: BLE001 — isolate to THIS group
                logger.warning("%s: prefill of %d failed: %s: %s",
                               self.name, len(reqs), type(e).__name__, e)
                self.metrics.observe_error(len(reqs))
                for req in reqs:
                    req.fail(BatchExecutionError(
                        f"prefill failed: {type(e).__name__}: {e}"))
                continue
            for i, req in enumerate(reqs):
                self._flag_abandoned(req)
                req.emit(first[i], self.name)
                self.metrics.observe_ttft(req.t_first - req.t_submit)
                self.metrics.observe_gen_tokens(1)
                if req.abandoned:
                    self._resolve(req, "abandoned")     # never seated, so
                    #                                     no slot eviction
                elif req.eos_id is not None \
                        and int(first[i]) == req.eos_id:
                    self._resolve(req, "eos")
                elif req.max_tokens == 1:
                    self._resolve(req, "length")
                else:
                    try:
                        req.slot = self.engine.admit(first[i], rows[i],
                                                     lengths[i],
                                                     tokens=req.prompt)
                    except InsufficientBlocksError:
                        # the pool budget raced CoW growth: the token is
                        # already delivered, so the request continues as
                        # a preemption (re-seat + teacher-forced replay)
                        self._preempted.append(req)
                        continue
                    except Exception as e:    # noqa: BLE001 — the slot
                        # write is a device op like step/prefill; a
                        # failure may have consumed the donated cache, so
                        # fail everything in flight (incl. this group's
                        # rest) and reset; later groups get fresh state
                        self._fail_all_inflight(
                            e, extra=[req] + reqs[i + 1:])
                        break
                    self._by_slot[req.slot] = req
                    req.slot_span = obstrace.start_span(
                        "slot", ctx=req.trace_ctx, root=False,
                        slot=int(req.slot), mode="prefill",
                        bucket=int(bucket))

    def _seat_reconstructed(self, reqs):
        """Seat requests whose context must be rebuilt without
        re-emitting (continuations + paged prefix-cache hits) through
        ``engine.seat_prefilled``; pool-dry items defer to ``_waiting``."""
        live = []
        for req in reqs:
            if self._flag_abandoned(req):
                self._resolve(req, "abandoned")
            else:
                live.append(req)
        if not live:
            return
        outcomes = self.engine.seat_prefilled([r.context for r in live])
        hard = None
        for req, out in zip(live, outcomes):
            if isinstance(out, InsufficientBlocksError):
                self._waiting.append(req)     # space, not failure: retry
            elif isinstance(out, BaseException):
                hard = out
                self.metrics.observe_error(1)
                req.fail(BatchExecutionError(
                    f"seat failed: {type(out).__name__}: {out}"))
            else:
                req.slot, req.replay_feed = out
                self._by_slot[req.slot] = req
                if req.replay_ctx is not None:
                    mode = "continuation"
                elif not self.engine.chunked or req.admit_covered:
                    mode = "prefix_hit"
                else:
                    mode = "prefill"        # fresh chunked admission
                req.slot_span = obstrace.start_span(
                    "slot", ctx=req.trace_ctx, root=False,
                    slot=int(req.slot), mode=mode,
                    chunked=self.engine.chunked,
                    teacher_forced=len(req.replay_feed))
        if hard is not None:
            # the failed seat was a device op (prefill / admit /
            # seat_cached) that may have consumed the donated cache —
            # fail everything in flight and reset, exactly like the
            # fresh-admission path, instead of stepping a possibly-
            # deleted buffer
            self._fail_all_inflight(hard)

    def _reseat_preempted(self):
        """Re-seat pool-preempted requests (oldest first) from prompt +
        delivered tokens — ``seat_prefilled`` reconstructs the slot and
        the teacher-forced replay swallows every re-derived emission, so
        the client's stream continues bit-identically.  Items the pool
        still cannot hold stay preempted for the next cycle."""
        if not self._preempted or not self.engine.free_slots:
            return
        batch = self._preempted[:self.engine.free_slots]
        rest = self._preempted[len(batch):]
        self._preempted = rest
        live, fulls = [], []
        for req in batch:
            if self._flag_abandoned(req):
                self._resolve(req, "abandoned")
                continue
            live.append(req)
            fulls.append(np.concatenate(
                [req.context, np.asarray(req.tokens, np.int32)]))
        if not live:
            return
        outcomes = self.engine.seat_prefilled(fulls)
        hard = None
        for req, out in zip(live, outcomes):
            if isinstance(out, InsufficientBlocksError):
                self._preempted.append(req)
            elif isinstance(out, BaseException):
                hard = out
                self.metrics.observe_error(1)
                req.fail(BatchExecutionError(
                    f"re-seat after pool preemption failed: "
                    f"{type(out).__name__}: {out}"))
            else:
                req.slot, req.replay_feed = out
                self._by_slot[req.slot] = req
                if req.slot_span is obstrace.NULL:
                    req.slot_span = obstrace.start_span(
                        "slot", ctx=req.trace_ctx, root=False,
                        slot=int(req.slot), mode="reseat",
                        teacher_forced=len(req.replay_feed))
                else:
                    req.slot_span.event("reseated", slot=int(req.slot),
                                        teacher_forced=len(
                                            req.replay_feed))
                self.metrics.observe_slot_reprefill()
        if hard is not None:
            # same donated-cache safety as _seat_reconstructed: the
            # failed seat was a device op — never step a possibly-
            # consumed buffer
            self._fail_all_inflight(hard)

    def _load_chunks(self):
        """Chunked mode, strictly between steps: arm each feeding slot's
        next up-to-(K-1)-token chunk (prompt ingestion, continuation
        replay, recovery replay — one mechanism), bounded by the
        engine's per-step chunk budget.  Lane counts are DATA: mixing
        decode rows with chunking rows never retraces.  A slot that gets
        no lanes this step (budget spent) still advances one
        teacher-forced token through its lane 0, so feeding always makes
        progress."""
        kk = self.engine.prefill_chunk
        budget = self.engine.prefill_chunk_budget
        used = 0
        for slot, req in self._by_slot.items():
            if not req.replay_feed or kk < 2:
                continue
            n = min(kk - 1, len(req.replay_feed))
            if budget:
                n = min(n, budget - used)
            if n <= 0:
                continue
            self.engine.load_chunk(slot, req.replay_feed[:n])
            used += n
            req.slot_span.event("prefill_chunk", lanes=int(n),
                                pos=int(self.engine._pos[slot]))

    def _load_spec(self):
        """Speculative mode, strictly between steps (after
        ``_load_chunks``): one batched draft rollout drains every active
        slot's committed-token feed, then draft lanes arm for the slots
        that are PURELY decoding — a slot still chunk-ingesting keeps
        its prefill lanes and joins speculation once its feed drains, so
        ingestion and speculation coexist across slots in the SAME step.
        Budgets cap each verify span at the request's remaining emission
        allowance (a run can never overshoot max_tokens)."""
        budgets = {}
        for slot, req in self._by_slot.items():
            if req.replay_feed or req.abandoned:
                continue
            budgets[slot] = req.max_tokens - len(req.tokens)
        for slot, k_eff in self.engine.speculate(budgets).items():
            self._by_slot[slot].slot_span.event(
                "speculate", k=int(k_eff),
                pos=int(self.engine._pos[slot]))

    def _emit_spec_run(self, req, slot, run):
        """Deliver one verify step's accepted run (matched drafts + the
        target's own token at the first mismatch) with full per-token
        semantics: EOS inside the run finishes the stream THERE (the
        trailing accepted tokens are discarded — the engine never
        advances past what was delivered), and max_tokens can end it
        mid-run.  A surviving stream advances past the whole run in one
        ``advance(consumed=)``."""
        emitted = 0
        for tok in run:
            first_emit = req.t_first is None
            req.emit(tok, self.name)
            emitted += 1
            if first_emit:
                req.slot_span.event("first_token")
                self.metrics.observe_ttft(req.t_first - req.t_submit)
                if req.replay_ctx is None:
                    self.engine.register_context(slot, req.prompt)
            self.metrics.observe_gen_tokens(1)
            if req.eos_id is not None and tok == req.eos_id:
                req.slot_span.event("accept", accepted=len(run) - 1,
                                    emitted=emitted, finish="eos")
                self._finish(req, "eos")
                return
            if len(req.tokens) >= req.max_tokens:
                req.slot_span.event("accept", accepted=len(run) - 1,
                                    emitted=emitted, finish="length")
                self._finish(req, "length")
                return
        req.slot_span.event("accept", accepted=len(run) - 1,
                            emitted=emitted)
        self.engine.advance(slot, run[-1], len(run))

    def _snap_breaker(self):
        """Mirror the breaker's state into the metrics gauge."""
        b = self.supervisor.breaker
        self.metrics.set_breaker_state(b.state, b.opened_total)

    def _recover_inflight(self, e):
        """The supervised step failed (error or watchdog trip): rebuild
        the slab from the AOT cache (``reset()`` — the compiled step is
        jit-cached, so the rebuild costs ZERO new traces) and re-prefill
        every in-flight request from prompt + tokens-generated-so-far,
        continuing each greedy stream bit-identically
        (``Supervisor.reprefill``).  A request whose replay outgrew the
        prefill ladder or whose recovery budget ran out fails with the
        cause; everything else keeps streaming."""
        sup = self.supervisor
        victims = list(self._by_slot.values())
        self._by_slot.clear()
        logger.warning("%s: supervised step over %d request(s) failed: "
                       "%s: %s — rebuilding slab + re-prefilling",
                       self.name, len(victims), type(e).__name__, e)
        # the rebuild-and-reprefill window as one span: a recovered
        # stream's trace shows exactly how long the failure stalled it
        recover_sp = obstrace.start_span("supervisor.recover",
                                         root=False, n=len(victims),
                                         cause=type(e).__name__)
        self.engine.reset()     # bumps the epoch: a hung stale step can
        #                         never commit into the rebuilt slab
        # eviction reasons are counted per OUTCOME below: a victim that
        # re-seats counts "recovered"; one whose caller left counts
        # "abandoned"; one that cannot be recovered counts "error"
        recoverable = []
        for req in victims:
            if req.future in self._abandoned:
                self._abandoned.discard(req.future)
                req.abandoned = True
            if req.abandoned:
                self.metrics.evict_slot("abandoned")
                self._resolve(req, "abandoned")
                continue
            req.recoveries += 1
            if req.recoveries > sup.max_request_recoveries:
                self.metrics.evict_slot("error")
                self.metrics.observe_error(1)
                req.fail(BatchExecutionError(
                    f"request failed after {req.recoveries - 1} slot "
                    f"recoveries: {type(e).__name__}: {e}"))
                continue
            recoverable.append(req)
        if not recoverable:
            recover_sp.end(recovered=0)
            return
        # same-bucket victims re-prefill as ONE engine batch; each
        # result is (slot, replay_feed) or the exception for that victim
        try:
            outcomes = sup.reprefill(self.engine,
                                     [(req.context, req.tokens)
                                      for req in recoverable])
        except Exception as re:    # noqa: BLE001 — an unexpected recovery
            # crash must fail the victims, never the worker thread
            outcomes = [re] * len(recoverable)
        for req, out in zip(recoverable, outcomes):
            if isinstance(out, InsufficientBlocksError):
                # space, not failure: the rebuilt pool starts with an
                # empty prefix index, so victims that shared blocks may
                # not all fit privately at once.  Park the overflow —
                # _reseat_preempted replays it bit-identically once
                # blocks free up, same as any pool-pressure preemption.
                self.metrics.evict_slot("pool_exhausted")
                self._preempted.append(req)
                continue
            if isinstance(out, BaseException):
                self.metrics.evict_slot("error")
                self.metrics.observe_error(1)
                req.fail(BatchExecutionError(
                    f"slot recovery failed: {type(out).__name__}: {out} "
                    f"(after step failure: {type(e).__name__}: {e})"))
                continue
            req.slot, req.replay_feed = out
            self._by_slot[req.slot] = req
            req.slot_span.event("recovery_reprefill",
                                slot=int(req.slot),
                                teacher_forced=len(req.replay_feed))
            self.metrics.evict_slot("recovered")
            self.metrics.observe_slot_reprefill()
        recover_sp.end(recovered=len(self._by_slot))

    def _fail_all_inflight(self, e, extra=()):
        """A device operation (step or slot admission) failed: fail every
        in-flight request (plus ``extra`` ones caught mid-admission) with
        the cause, reset the engine (the donated slab may be consumed),
        and let the loop keep serving."""
        victims = list(self._by_slot.values()) + list(extra)
        logger.warning("%s: device op over %d request(s) failed: %s: %s",
                       self.name, len(victims), type(e).__name__, e)
        self.metrics.observe_error(len(victims))
        for req in victims:
            req.fail(BatchExecutionError(
                f"decode batch failed: {type(e).__name__}: {e}"))
        for _ in self._by_slot:
            self.metrics.evict_slot("error")
        self._by_slot.clear()
        self.engine.reset()

    def _loop(self):
        while True:
            if self._closed.is_set() and not self._drain:
                # the worker owns slot state: fail the in-flight requests
                # here, never from close()'s thread
                for slot, req in list(self._by_slot.items()):
                    req.fail(ShutdownError(
                        "generation batcher closed without drain"))
                    self.engine.evict(slot, "shutdown")
                self._by_slot.clear()
                for req in self._preempted + list(self._waiting):
                    req.fail(ShutdownError(
                        "generation batcher closed without drain"))
                self._preempted, self._waiting = [], collections.deque()
                return
            # host-tier restores land HERE — strictly between steps: the
            # staged chunks write into their claimed blocks and the
            # chain publishes into the prefix index, so a deferred
            # request's next retry seats as an ordinary resident hit
            self.engine.poll_restores()
            # cross-replica exports land here too: same between-steps
            # seam, same committed-cache safety as the restore commits
            self._serve_exports()
            self._admit_from_queue(block=not self._by_slot)
            if not self._by_slot:
                if self._closed.is_set() and self._q.empty() \
                        and not self._waiting and not self._preempted:
                    return
                if self._waiting:
                    # every runnable request is deferred (restore in
                    # flight / pool dry): wait a tick on the transfer
                    # thread instead of hot-spinning the retry loop
                    self.engine.poll_restores(timeout=0.005)
                continue
            sup = self.supervisor
            if self.engine.chunked:
                self._load_chunks()
                if self.engine.speculating:
                    self._load_spec()
            try:
                # paged layout: provision every active slot's write block
                # (chain growth + copy-on-write forks) strictly BETWEEN
                # steps; pool exhaustion preempts the youngest slots —
                # their requests re-seat via _reseat_preempted and their
                # streams continue bit-identically
                for slot in self.engine.prepare_step():
                    req = self._by_slot.pop(slot)
                    req.slot = None
                    req.slot_span.event("preempted",
                                        reason="pool_exhausted")
                    self._preempted.append(req)
                if not self._by_slot:
                    continue        # everything was preempted
                if sup is None:
                    nxt = self.engine.step()
                else:
                    try:
                        nxt = sup.run_step(self.engine)
                    except WatchdogTimeout:
                        self.metrics.observe_watchdog_trip()
                        raise
                    sup.breaker.record_success()
                    self._snap_breaker()
            except Exception as e:    # noqa: BLE001 — isolate to the
                # requests in flight; the loop keeps serving
                if sup is not None:
                    opened = sup.breaker.record_failure()
                    self._snap_breaker()
                    if opened:
                        logger.warning(
                            "%s: circuit breaker OPEN after %d consecutive "
                            "step failures; shedding new admissions for "
                            "%.1fs", self.name, sup.breaker.threshold,
                            sup.breaker.cooldown_s)
                    self._recover_inflight(e)
                else:
                    self._fail_all_inflight(e)
                continue
            for slot, req in list(self._by_slot.items()):
                if req.future in self._abandoned:
                    # abandon() raced the seating window: the flag landed
                    # in the set after admission's check — honor it here
                    self._abandoned.discard(req.future)
                    req.abandoned = True
                if req.abandoned:
                    self._finish(req, "abandoned")
                    continue
                # lanes this step processed for the slot (1 = plain
                # decode; >1 = a prefill/replay chunk, chunked mode)
                consumed = self.engine.chunk_len(slot)
                if req.replay_feed:
                    if len(req.replay_feed) >= consumed:
                        # teacher-forced feeding continues: this step's
                        # emission re-derives an already-known token —
                        # swallow it and feed the recorded stream, until
                        # the slot reaches the end of its context
                        self.engine.advance(
                            slot, req.replay_feed[consumed - 1],
                            consumed)
                        del req.replay_feed[:consumed]
                        continue
                    # the feed drained EXACTLY at this step's last lane:
                    # its emission is the first real one — fall through
                    del req.replay_feed[:]
                if self.engine.speculating:
                    run = self.engine.take_spec_result(slot)
                    if run is not None:
                        # a verify step: the whole accepted run emits in
                        # one go (and does its own advance/finish)
                        self._emit_spec_run(req, slot, run)
                        continue
                tok = int(nxt[slot])
                first_emit = req.t_first is None
                req.emit(tok, self.name)
                if first_emit:
                    # chunked admissions and continuations reach their
                    # first token HERE (the fresh-prompt ladder path
                    # records it at prefill instead)
                    req.slot_span.event("first_token")
                    self.metrics.observe_ttft(req.t_first - req.t_submit)
                    if self.engine.chunked and req.replay_ctx is None:
                        # the prompt's K/V is fully resident exactly
                        # now: publish it to the paged prefix index
                        # (no-op on slab), the chunked twin of the
                        # ladder path's admit-time registration
                        self.engine.register_context(slot, req.prompt)
                self.metrics.observe_gen_tokens(1)
                if req.eos_id is not None and tok == req.eos_id:
                    self._finish(req, "eos")
                elif len(req.tokens) >= req.max_tokens:
                    self._finish(req, "length")
                else:
                    self.engine.advance(slot, tok, consumed)

    # ------------------------------------------------------------ shutdown

    def close(self, drain=True, timeout=60.0):
        """Stop admissions, then either finish every queued AND in-flight
        generation (drain=True) or fail them (drain=False).  Idempotent."""
        with self._admit_lock:
            self._drain = drain
            self._closed.set()
        try:
            self.metrics.queue_depth_fns.remove(self._depth_fn)
        except ValueError:
            pass                    # already removed (idempotent close)
        self._thread.join(timeout)
        if self._thread.is_alive():
            # a wedged step: slot state belongs to the (still running)
            # worker — touching _by_slot or the engine from here would
            # race it; callers' own result() timeouts bound their wait
            logger.warning("%s: worker did not drain within %.0fs; "
                           "leaving in-flight slots to it", self.name,
                           timeout)
        # empty anything still queued (a submit that raced the close, or
        # drain=False leftovers) — the queue is thread-safe either way
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            self.metrics.reject("shutdown")
            req.fail(ShutdownError("generation batcher closed"))

    @property
    def closed(self):
        return self._closed.is_set()

    @property
    def ready(self):
        """Readiness (/readyz): accepting work, the engine is warm, and
        the circuit breaker is not OPEN.  Half-open counts ready: the
        balancer must route again or the probe that would reclose the
        breaker could never arrive (non-probe admits shed with
        Retry-After, which is the breaker doing its job)."""
        if self._closed.is_set() or not self.engine.ready:
            return False
        return self.supervisor is None \
            or self.supervisor.breaker.state != "open"

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
