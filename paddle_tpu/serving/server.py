"""Stdlib HTTP front-end + CLI for the serving runtime.

The reference served models from C++ services over the C API; the
TPU-native runtime's front door is a dependency-free JSON/HTTP server on
``http.server.ThreadingHTTPServer`` — each connection thread blocks on its
request's Future while the single batcher thread forms engine batches, so
concurrency comes from the batcher, not from the HTTP layer.

Endpoints:
  POST /v1/infer   {"feed": {slot: array}, "deadline_ms": optional}
                   -> {"outputs": ..., "latency_ms": ...}
                   errors map to status codes: invalid feed/JSON 400,
                   overload 429, shutdown 503, deadline 504, batch
                   failure 500 — always a JSON body with "error".
  GET  /healthz    200 {"status": "ok", ...} (503 once draining)
  GET  /metrics    Prometheus text (serving/metrics.py)

CLI (``python -m paddle_tpu.serving``):
  --artifact model.shlo            one-bucket exported artifact
  --artifacts 'model.b*.shlo'      bucket ladder (export.export_bucketed)
  --demo                           built-in tiny MLP (smoke/bring-up)
  --buckets 1,4,16 --port N --max-delay-ms --queue-size --deadline-ms
  --smoke                          self-test: ephemeral port, concurrent
                                   requests, /metrics sanity, ONE JSON
                                   line, exit code (healthy_window.sh's
                                   serving phase)

The JSON front-end serves plain-array feed slots (dense/index vectors);
structured SequenceBatch slots are an in-process engine feature.
SIGTERM drains gracefully: stop admissions, finish queued requests,
answer in-flight connections, then exit.
"""

import argparse
import json
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import jax

from paddle_tpu.serving.batcher import (Batcher, DeadlineExceededError,
                                        OverloadedError, ShutdownError)
from paddle_tpu.serving.engine import InferenceEngine, InvalidRequestError
from paddle_tpu.utils.logging import logger

_STATUS = ((InvalidRequestError, 400), (OverloadedError, 429),
           (ShutdownError, 503), (DeadlineExceededError, 504))


def _json_to_row(engine, obj):
    """JSON feed dict -> per-row numpy feed matching the engine spec
    (dtype cast here; shape checking is the engine's job)."""
    if not isinstance(obj, dict):
        raise InvalidRequestError("'feed' must be an object of "
                                  "{slot: array}")
    spec_row = engine.bucket_spec(1)
    if not isinstance(spec_row, dict):
        raise InvalidRequestError(
            "this model's feed is not a flat dict; the JSON front-end "
            "serves plain-array slots only")
    row = {}
    for name, sds in spec_row.items():
        if not isinstance(sds, jax.ShapeDtypeStruct):
            raise InvalidRequestError(
                f"feed slot {name!r} is structured (SequenceBatch); the "
                "JSON front-end serves plain-array slots only")
        if name not in obj:
            raise InvalidRequestError(f"missing feed slot {name!r}")
        try:
            row[name] = np.asarray(obj[name], dtype=sds.dtype)
        except (TypeError, ValueError) as e:
            raise InvalidRequestError(
                f"feed slot {name!r}: cannot convert to {sds.dtype}: {e}") \
                from e
    extra = sorted(set(obj) - set(spec_row))
    if extra:
        raise InvalidRequestError(f"unknown feed slot(s) {extra}")
    return row


def _to_jsonable(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a).tolist(), tree)


class ServingHandler(BaseHTTPRequestHandler):
    # one server == one model; the batcher hangs off the server object
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # route access logs to our logger
        logger.debug("http: " + fmt, *args)

    def _reply(self, code, payload, content_type="application/json"):
        body = (payload if isinstance(payload, bytes)
                else json.dumps(payload).encode())
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # ------------------------------------------------------------ GET

    def do_GET(self):
        batcher = self.server.batcher
        if self.path == "/healthz":
            draining = batcher.closed
            self._reply(503 if draining else 200, {
                "status": "draining" if draining else "ok",
                "model": batcher.engine.name,
                "buckets": list(batcher.engine.buckets),
                "queue_depth": batcher.metrics.queue_depth(),
            })
        elif self.path == "/metrics":
            self._reply(200, batcher.metrics.render_prometheus().encode(),
                        content_type="text/plain; version=0.0.4")
        else:
            self._reply(404, {"error": f"no route {self.path!r}"})

    # ------------------------------------------------------------ POST

    def do_POST(self):
        if self.path != "/v1/infer":
            self._reply(404, {"error": f"no route {self.path!r}"})
            return
        t0 = time.perf_counter()
        batcher = self.server.batcher
        try:
            length = int(self.headers.get("Content-Length") or 0)
            try:
                req = json.loads(self.rfile.read(length) or b"")
            except ValueError as e:
                raise InvalidRequestError(f"malformed JSON: {e}") from e
            if not isinstance(req, dict) or "feed" not in req:
                raise InvalidRequestError('body must be {"feed": {...}}')
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None and (
                    not isinstance(deadline_ms, (int, float))
                    or deadline_ms <= 0):
                raise InvalidRequestError("deadline_ms must be a positive "
                                          "number")
            row = _json_to_row(batcher.engine, req["feed"])
            fut = batcher.submit(row, deadline_ms=deadline_ms)
            # bounded wait: batch errors surface here; the timeout is a
            # backstop against a wedged engine, not a policy knob (use
            # deadline_ms for per-request deadlines)
            out = fut.result(timeout=600)
            self._reply(200, {
                "outputs": _to_jsonable(out),
                "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
            })
        except Exception as e:    # noqa: BLE001 — every error is a response
            for etype, code in _STATUS:
                if isinstance(e, etype):
                    break
            else:
                code = 500
            self._reply(code, {"error": f"{type(e).__name__}: {e}"})


def make_server(batcher, host="127.0.0.1", port=0):
    """Bind (port 0 = ephemeral) and return the server; caller runs
    ``serve_forever()``.  ``server.port`` carries the bound port."""
    httpd = ThreadingHTTPServer((host, port), ServingHandler)
    httpd.daemon_threads = True
    httpd.batcher = batcher
    httpd.port = httpd.server_address[1]
    return httpd


# ------------------------------------------------------------------- CLI


def _demo_engine(buckets, warm=True):
    """Built-in tiny MLP engine — bring-up/smoke without an artifact."""
    from paddle_tpu.layers import api as L
    from paddle_tpu.layers.graph import Topology, reset_names
    reset_names()
    x = L.data_layer("serving_demo_x", size=16)
    h = L.fc_layer(input=x, size=32, act="tanh")
    out = L.fc_layer(input=h, size=4, act="softmax")
    params = Topology([out]).init(jax.random.PRNGKey(0))
    spec = {"serving_demo_x": jax.ShapeDtypeStruct((1, 16), np.float32)}
    return InferenceEngine.from_topology(out, params, spec, buckets=buckets,
                                         warm=warm, name="demo")


def _build_engine(args):
    if args.artifact:
        return InferenceEngine.from_artifact(args.artifact)
    if args.artifacts:
        return InferenceEngine.from_artifacts(args.artifacts)
    if args.demo:
        buckets = tuple(int(b) for b in args.buckets.split(","))
        return _demo_engine(buckets)
    raise SystemExit("serving: pass one of --artifact PATH, "
                     "--artifacts GLOB, --demo")


def _zeros_row_json(engine, fill=0.5):
    """A valid JSON feed for this engine's spec (smoke traffic)."""
    row = {}
    for name, sds in engine.bucket_spec(1).items():
        shape = tuple(sds.shape[1:])
        if np.issubdtype(sds.dtype, np.integer):
            row[name] = np.zeros(shape, sds.dtype).tolist()
        else:
            row[name] = np.full(shape, fill, sds.dtype).tolist()
    return row


def _smoke(batcher, n_requests=8):
    """Self-contained serving smoke: ephemeral port, n concurrent HTTP
    requests, a malformed request, /healthz + /metrics sanity.  Prints ONE
    JSON line; returns the process exit code (healthy_window.sh phase)."""
    import urllib.error
    import urllib.request

    httpd = make_server(batcher, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.port}"
    feed = _zeros_row_json(batcher.engine)
    ok = [0]
    errs = []

    def hit(i):
        body = json.dumps({"feed": feed}).encode()
        try:
            with urllib.request.urlopen(urllib.request.Request(
                    f"{base}/v1/infer", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=30) as r:
                resp = json.loads(r.read())
                if "outputs" in resp:
                    ok[0] += 1
        except Exception as e:    # noqa: BLE001
            errs.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=hit, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)

    # malformed JSON must 400 without wounding the engine
    bad_status = None
    try:
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/v1/infer", data=b"{not json",
            headers={"Content-Type": "application/json"}), timeout=30)
    except urllib.error.HTTPError as e:
        bad_status = e.code
    with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
        health = json.loads(r.read())
    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
        metrics_text = r.read().decode()

    snap = batcher.metrics.snapshot()
    name = batcher.metrics.name
    metrics_sane = (
        f"{name}_requests_total {snap['requests_total']}" in metrics_text
        and f"{name}_batches_total" in metrics_text
        and 'latency_seconds{quantile="0.50"}' in metrics_text
        and snap["responses_total"] == ok[0]
        and snap["batches_total"] >= 1)
    out = {
        "metric": "serving smoke (dynamic batcher + HTTP front-end)",
        "value": ok[0], "unit": f"requests_ok/{n_requests}",
        "vs_baseline": None,
        "bad_request_status": bad_status,
        "healthz": health.get("status"),
        "metrics_sane": bool(metrics_sane),
        "mean_occupancy": snap["mean_occupancy"],
        "p50_ms": snap["latency_ms"]["p50"],
        "p99_ms": snap["latency_ms"]["p99"],
    }
    if errs:
        out["errors"] = errs[:5]
    httpd.shutdown()
    batcher.close()
    print(json.dumps(out), flush=True)
    passed = (ok[0] == n_requests and bad_status == 400
              and health.get("status") == "ok" and metrics_sane)
    return 0 if passed else 2


def main(argv=None):
    from paddle_tpu.utils.flags import FLAGS
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving",
        description="dynamic-batching inference server")
    ap.add_argument("--artifact", help="exported StableHLO artifact")
    ap.add_argument("--artifacts",
                    help="glob of bucketed artifacts (model.b*.shlo)")
    ap.add_argument("--demo", action="store_true",
                    help="serve the built-in tiny MLP")
    ap.add_argument("--buckets", default=FLAGS.serving_buckets,
                    help="batch bucket ladder for --demo (artifacts carry "
                         "their own)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=FLAGS.serving_port)
    ap.add_argument("--max-batch-size", type=int,
                    default=FLAGS.serving_max_batch_size or None)
    ap.add_argument("--max-delay-ms", type=float,
                    default=FLAGS.serving_max_delay_ms)
    ap.add_argument("--queue-size", type=int,
                    default=FLAGS.serving_queue_size)
    ap.add_argument("--deadline-ms", type=float,
                    default=FLAGS.serving_deadline_ms or None)
    ap.add_argument("--smoke", action="store_true",
                    help="self-test on an ephemeral port, print one JSON "
                         "line, exit")
    args = ap.parse_args(argv)
    if args.smoke and not (args.artifact or args.artifacts):
        args.demo = True
    if args.smoke:
        # a generous batch window so the smoke's concurrent clients
        # reliably coalesce (the occupancy>1 assertion) even on a loaded
        # CI machine
        args.max_delay_ms = max(args.max_delay_ms, 50.0)

    engine = _build_engine(args)
    batcher = Batcher(engine, max_batch_size=args.max_batch_size,
                      max_delay_ms=args.max_delay_ms,
                      queue_size=args.queue_size,
                      default_deadline_ms=args.deadline_ms)
    if args.smoke:
        return _smoke(batcher)

    httpd = make_server(batcher, args.host, args.port)
    logger.info("serving %s on http://%s:%d (buckets %s, max_delay %.1fms, "
                "queue %d)", engine.name, args.host, httpd.port,
                list(engine.buckets), args.max_delay_ms, args.queue_size)

    def _drain(signum, frame):
        logger.info("SIGTERM: draining (no new admissions, finishing "
                    "queued requests)")
        threading.Thread(target=httpd.shutdown, daemon=True).start()
    try:
        signal.signal(signal.SIGTERM, _drain)
        signal.signal(signal.SIGINT, _drain)
    except ValueError:
        pass        # not the main thread (embedded use)
    try:
        httpd.serve_forever()
    finally:
        # order matters: the drain resolves every in-flight future, THEN
        # server_close() joins the handler threads (block_on_close) so
        # their responses reach the sockets before the interpreter exits
        # — otherwise the work the drain completed is dropped on the wire
        batcher.close(drain=True)
        httpd.server_close()
        logger.info("serving stopped; %d responses served",
                    batcher.metrics.responses_total)
    return 0


if __name__ == "__main__":
    sys.exit(main())
