"""Stdlib HTTP front-end + CLI for the serving runtime.

The reference served models from C++ services over the C API; the
TPU-native runtime's front door is a dependency-free JSON/HTTP server on
``http.server.ThreadingHTTPServer`` — each connection thread blocks on its
request's Future while the single batcher thread forms engine batches, so
concurrency comes from the batcher, not from the HTTP layer.

Endpoints:
  POST /v1/infer   {"feed": {slot: array}, "deadline_ms": optional}
                   -> {"outputs": ..., "latency_ms": ...}
                   errors map to status codes: invalid feed/JSON 400,
                   overload 429, shutdown/breaker 503, deadline 504,
                   batch failure 500 — always a JSON body with "error";
                   429/503 carry a Retry-After header (breaker- and
                   queue-depth-derived; docs/serving.md §6).
  POST /v1/generate {"prompt": [ids], "max_tokens": N, "eos_id": opt,
                    "deadline_ms": opt, "stream": false}
                   -> {"tokens": [...], "finish_reason": "eos"|"length",
                       "ttft_ms": ..., "latency_ms": ...}
                   "stream": true streams newline-delimited JSON chunks
                   ({"token": id} per emitted token, then a {"done":
                   true, ...} record) over chunked transfer encoding —
                   continuous-batching generation (decode_engine.py,
                   docs/serving.md §4); same error-code mapping.
  GET  /healthz    LIVENESS: 200 while the process is alive (even
                   draining — a balancer uses /readyz to route)
  GET  /readyz     READINESS: 200 when warm-up is complete, the circuit
                   breaker is closed, and no drain has begun; 503 (with
                   the blocking reasons and Retry-After) otherwise
  POST /v1/kv/export {"tokens": [ids]}
                   -> the longest resident KV coverage of that prefix as
                   one length-prefixed spill-format blob (application/
                   octet-stream), serialized by the batcher worker
                   strictly BETWEEN decode steps; 404 = no coverage (the
                   peer recomputes).  The disaggregated-serving
                   transport (serving/transfer.py; docs/serving.md
                   "Disaggregated serving").
  GET  /metrics    Prometheus text (serving/metrics.py)
  GET  /debug/traces  recent request spans + slowest-request trace_ids
                   (obs/trace.py; {"enabled": false} when tracing is
                   off — enable with --obs-trace; docs/observability.md)

CLI (``python -m paddle_tpu.serving``):
  --artifact model.shlo            one-bucket exported artifact
  --artifacts 'model.b*.shlo'      bucket ladder (export.export_bucketed)
  --demo                           built-in tiny MLP (smoke/bring-up)
  --demo-generate                  built-in tiny LM trunk behind the
                                   continuous-batching /v1/generate
  --buckets 1,4,16 --port N --max-delay-ms --queue-size --deadline-ms
  --gen-slots --gen-max-len --gen-prefill-buckets --gen-max-tokens
  --smoke                          self-test: ephemeral port, concurrent
                                   requests, /metrics sanity, ONE JSON
                                   line, exit code (healthy_window.sh's
                                   serving phase)
  --smoke-generate                 generation self-test: concurrent
                                   staggered /v1/generate requests,
                                   streaming, EOS early-finish, ONE JSON
                                   line (healthy_window.sh phase 8)
  --kv-layout slab|paged           decode KV-cache layout (paged = block
                                   pool + prefix sharing, kv_pool.py)
  --kv-block-size --kv-num-blocks --kv-prefix-cache
  --kv-host-bytes N                host-RAM spill-tier cap: evicted
                                   prefix chains spill to host and
                                   restore asynchronously on the next
                                   hit (0 = tier off; docs/serving.md
                                   "Hierarchical KV")
  --smoke-paged                    paged-KV self-test: shared-system-
                                   prompt clients, prefix hits + CoW
                                   fork, streams bit-identical to the
                                   slab twin, ONE JSON line
                                   (healthy_window.sh phase 11)
  --smoke-spill                    hierarchical-KV self-test: churn
                                   evicts the shared chain, the
                                   returning prefix restore-hits with
                                   zero chunk lanes, bit-identical to
                                   the tier-less twin, ONE JSON line
                                   (healthy_window.sh phase 20)
  --role prefill|decode|mixed      disaggregated-serving role advertised
                                   on /metrics (serving_role{role=...}):
                                   the router prefers prefill replicas
                                   for new prompts and hands streams to
                                   decode replicas at the first token,
                                   shipping the KV chain over
                                   /v1/kv/export (serving/transfer.py;
                                   docs/serving.md "Disaggregated
                                   serving"); mixed (default) = both
  --prefill-chunk K                unified chunked prefill (the
                                   default): prompt ingestion rides the
                                   ONE decode step as K-token chunks;
                                   0 = the legacy prefill ladder
                                   (docs/serving.md "Chunked prefill")
  --smoke-chunked                  chunked-prefill self-test: a long
                                   prompt admitted MID-DECODE chunks
                                   through the step while in-flight
                                   streams keep emitting, all streams
                                   bit-identical to the ladder twin,
                                   ONE JSON line (healthy_window.sh
                                   phase 15)
  --kv-dtype float32|int8          quantized KV cache (int8 + per-head
                                   scale sidecars; paged auto-sizing
                                   doubles the block count at equal
                                   bytes — docs/serving.md "Quantized
                                   serving")
  --quant-weights 1                per-channel int8 trunk weights
                                   (quant/weights.py)
  --smoke-quant                    quantized-serving self-test: int8-KV
                                   engine within the committed quality
                                   budget vs the fp32 twin, int8+weights
                                   exact vs the quantized oracle,
                                   kv_blocks_total doubled, ONE JSON
                                   line (healthy_window.sh phase 16)
  --smoke-quant-prefill            end-to-end low-precision self-test:
                                   int8 flash prefill within the logit
                                   budget vs the fp32 twin, int8 cache
                                   bit-exact vs sequential steps, int8
                                   trainer 3-step loss parity, ONE JSON
                                   line (healthy_window.sh phase 22)
  --speculate-k K                  speculative decoding: a truncated-
                                   trunk draft proposes K tokens per
                                   slot, the one chunked step scores
                                   every lane, each step nets >= 1
                                   token; streams stay token-identical
                                   (docs/serving.md "Speculative
                                   decoding")
  --draft-layers N                 trunk depth of the derived draft
                                   (embedding/vocab shared)
  --smoke-speculative              speculative-decoding self-test: spec
                                   engine vs a non-spec twin, streams
                                   bit-identical, acceptance evidence
                                   in /metrics, ONE JSON line
                                   (healthy_window.sh phase 18)
  --mesh-shards N                  tensor-parallel sharded decode: the
                                   one chunked step runs under an
                                   N-chip model-axis mesh (head-striped
                                   attention + KV pool, vocab-striped
                                   embedding; docs/serving.md "Sharded
                                   decode"); 0/1 = single-chip
  --smoke-sharded                  sharded-decode self-test: n=2 forced
                                   host mesh (re-execs itself with
                                   XLA_FLAGS when single-device),
                                   staggered concurrent streams
                                   bit-identical to the single-chip
                                   twin, mesh evidence in /metrics, ONE
                                   JSON line (healthy_window.sh
                                   phase 19)

The JSON front-end serves plain-array feed slots (dense/index vectors);
structured SequenceBatch slots are an in-process engine feature.
SIGTERM drains gracefully: stop admissions, finish queued requests,
answer in-flight connections, then exit.
"""

import argparse
import json
import queue as _queue
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import jax

from paddle_tpu.obs import trace as obstrace
from paddle_tpu.resilience.supervisor import (BreakerOpenError, Supervisor,
                                              retry_transient)
from paddle_tpu.serving.batcher import (Batcher, DeadlineExceededError,
                                        OverloadedError, ShutdownError)
from paddle_tpu.serving.engine import InferenceEngine, InvalidRequestError
from paddle_tpu.serving import transfer as kv_transfer
from paddle_tpu.utils.logging import log_context, logger

_STATUS = ((InvalidRequestError, 400), (OverloadedError, 429),
           (BreakerOpenError, 503), (ShutdownError, 503),
           (DeadlineExceededError, 504))


def _retry_after_for(e, metrics, drain_timeout_s=None):
    """Retry-After seconds for a shedding response (429/503), derived
    from the shedding cause: breaker -> its remaining cooldown; overload
    -> expected queue drain time (depth x recent p50 batch time);
    drain -> the EFFECTIVE drain deadline (the --drain-timeout-s the
    server was started with, not the raw flag — the process is going
    away within that window)."""
    if isinstance(e, BreakerOpenError):
        return max(1, int(round(e.retry_after_s + 0.5)))
    if isinstance(e, OverloadedError):
        p50 = depth = 0
        if metrics is not None:
            # inference plane: per-batch engine time.  Generation plane:
            # batch_time only sees prefill batches (decode time lands in
            # tpot), so fall back to the request WALL latency — an over-
            # estimate under load, which errs toward clients backing off
            # longer (the safe direction), capped below.
            p50 = metrics.batch_time.percentiles((50,)).get(50, 0.0) \
                or metrics.latency.percentiles((50,)).get(50, 0.0)
            depth = metrics.queue_depth()
        return max(1, min(30, int(round(depth * p50 + 0.5))))
    if isinstance(e, ShutdownError):
        if drain_timeout_s is None:
            from paddle_tpu.utils.flags import FLAGS
            drain_timeout_s = FLAGS.serving_drain_timeout_s
        return max(1, int(drain_timeout_s))
    return None


def _json_to_row(engine, obj):
    """JSON feed dict -> per-row numpy feed matching the engine spec
    (dtype cast here; shape checking is the engine's job)."""
    if not isinstance(obj, dict):
        raise InvalidRequestError("'feed' must be an object of "
                                  "{slot: array}")
    spec_row = engine.bucket_spec(1)
    if not isinstance(spec_row, dict):
        raise InvalidRequestError(
            "this model's feed is not a flat dict; the JSON front-end "
            "serves plain-array slots only")
    row = {}
    for name, sds in spec_row.items():
        if not isinstance(sds, jax.ShapeDtypeStruct):
            raise InvalidRequestError(
                f"feed slot {name!r} is structured (SequenceBatch); the "
                "JSON front-end serves plain-array slots only")
        if name not in obj:
            raise InvalidRequestError(f"missing feed slot {name!r}")
        try:
            row[name] = np.asarray(obj[name], dtype=sds.dtype)
        except (TypeError, ValueError) as e:
            raise InvalidRequestError(
                f"feed slot {name!r}: cannot convert to {sds.dtype}: {e}") \
                from e
    extra = sorted(set(obj) - set(spec_row))
    if extra:
        raise InvalidRequestError(f"unknown feed slot(s) {extra}")
    return row


def _to_jsonable(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a).tolist(), tree)


class ServingHandler(BaseHTTPRequestHandler):
    # one server == one model; the batcher hangs off the server object
    protocol_version = "HTTP/1.1"
    # the request's root span (obs/trace.py), set by do_POST; GETs and
    # disabled tracing leave the NULL singleton (empty trace_id)
    _obs = obstrace.NULL

    def log_message(self, fmt, *args):   # route access logs to our logger
        logger.debug("http: " + fmt, *args)

    def _reply(self, code, payload, content_type="application/json",
               headers=None):
        body = (payload if isinstance(payload, bytes)
                else json.dumps(payload).encode())
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if self._obs.trace_id:
            self.send_header("X-Trace-Id", self._obs.trace_id)
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    # ------------------------------------------------------------ GET

    def do_GET(self):
        # keep-alive: one handler instance serves several requests, so
        # drop any previous POST's span before replying
        self._obs = obstrace.NULL
        # one server serves an inference batcher, a generation batcher,
        # or both; health/metrics report whichever exists.  Liveness vs
        # readiness (docs/serving.md §6): /healthz answers "is the
        # process alive" — 200 as long as we can answer at all, so an
        # orchestrator never kills a node that is merely draining or
        # warming; /readyz answers "should a balancer route here" — 503
        # while warm-up is incomplete, the circuit breaker is open, or a
        # drain has begun on EITHER plane.
        batchers = [b for b in (self.server.batcher,
                                self.server.gen_batcher) if b is not None]
        batcher = batchers[0]
        if self.path == "/healthz":
            draining = any(b.closed for b in batchers)
            engine = batcher.engine
            self._reply(200, {
                "status": "ok",
                "draining": draining,
                "model": engine.name,
                "buckets": list(getattr(engine, "buckets", None)
                                or getattr(engine, "prefill_buckets", ())),
                "queue_depth": batcher.metrics.queue_depth(),
            })
        elif self.path == "/readyz":
            reasons = []
            retry_after = 1.0
            for b in batchers:
                if b.closed:
                    reasons.append("draining")
                    # the process is going away within the drain window
                    retry_after = max(
                        retry_after,
                        getattr(self.server, "drain_timeout_s", None)
                        or 1.0)
                elif not b.engine.ready:
                    reasons.append("warming")
                elif not b.ready:
                    # warm and was accepting: either the breaker is open
                    # (supervised generation plane) or a close() raced
                    # these checks (any plane — report it as the drain
                    # it is)
                    sup = getattr(b, "supervisor", None)
                    if sup is not None \
                            and sup.breaker.state != "closed":
                        reasons.append("breaker_open")
                        retry_after = max(
                            retry_after,
                            sup.breaker.seconds_until_probe())
                    else:
                        reasons.append("draining")
            reasons = sorted(set(reasons))
            if reasons:
                self._reply(503, {"status": "unready", "reasons": reasons},
                            headers={"Retry-After":
                                     max(1, int(round(retry_after)))})
            else:
                self._reply(200, {"status": "ready"})
        elif self.path == "/metrics":
            self._reply(200, batcher.metrics.render_prometheus().encode(),
                        content_type="text/plain; version=0.0.4")
        elif self.path == "/debug/traces":
            # recent spans + the slowest recent requests' trace_ids
            # (obs/trace.py; {"enabled": false, ...} when tracing is off)
            self._reply(200, obstrace.debug_payload())
        else:
            self._reply(404, {"error": f"no route {self.path!r}"})

    # ------------------------------------------------------------ POST

    def _read_json(self):
        length = int(self.headers.get("Content-Length") or 0)
        try:
            req = json.loads(self.rfile.read(length) or b"")
        except ValueError as e:
            raise InvalidRequestError(f"malformed JSON: {e}") from e
        if not isinstance(req, dict):
            raise InvalidRequestError("body must be a JSON object")
        return req

    @staticmethod
    def _deadline_ms(req):
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is not None and (
                not isinstance(deadline_ms, (int, float))
                or deadline_ms <= 0):
            raise InvalidRequestError("deadline_ms must be a positive "
                                      "number")
        return deadline_ms

    def _error_reply(self, e, metrics=None):
        for etype, code in _STATUS:
            if isinstance(e, etype):
                break
        else:
            code = 500
        headers = {}
        if code in (429, 503):
            ra = _retry_after_for(
                e, metrics,
                drain_timeout_s=getattr(self.server, "drain_timeout_s",
                                        None))
            if ra is not None:
                headers["Retry-After"] = ra
        self._reply(code, {"error": f"{type(e).__name__}: {e}"},
                    headers=headers)

    def _submit_retrying(self, batcher, fn):
        """Submit with the bounded transient-failure retry policy
        (resilience/supervisor.py): exponential backoff + jitter, budget
        from the resilience_retry_budget flag, retries counted into
        /metrics.  Safe because submit's fault point fires before any
        queue mutation (idempotent failed attempts)."""
        from paddle_tpu.utils.flags import FLAGS
        return retry_transient(
            fn, budget=FLAGS.resilience_retry_budget,
            on_retry=lambda _a, _e: batcher.metrics.observe_retry())

    def do_POST(self):
        # root span for this request (obs/trace.py): a propagated
        # traceparent (the router's dispatch) CONTINUES that trace — one
        # trace_id then stitches router, every failover leg, and the
        # slot timeline; a direct client starts a fresh trace.  The
        # trace_id doubles as the log correlation id (log_context), is
        # echoed in the response body and the X-Trace-Id header.
        ctx = obstrace.extract(self.headers.get("traceparent"))
        with obstrace.span("server.request", ctx=ctx, root=True,
                           route=self.path) as sp, \
                log_context(trace_id=sp.trace_id,
                            request_id=sp.span_id):
            self._obs = sp
            self._route_post()

    def _route_post(self):
        if self.path == "/v1/generate":
            self._post_generate()
            return
        if self.path == kv_transfer.EXPORT_PATH:
            self._post_kv_export()
            return
        if self.path != "/v1/infer":
            self._reply(404, {"error": f"no route {self.path!r}"})
            return
        t0 = time.perf_counter()
        batcher = self.server.batcher
        if batcher is None:
            self._reply(404, {"error": "no inference model is being "
                                       "served (generation-only server)"})
            return
        try:
            req = self._read_json()
            if "feed" not in req:
                raise InvalidRequestError('body must be {"feed": {...}}')
            deadline_ms = self._deadline_ms(req)
            row = _json_to_row(batcher.engine, req["feed"])
            fut = self._submit_retrying(
                batcher, lambda: batcher.submit(row,
                                                deadline_ms=deadline_ms))
            # bounded wait: batch errors surface here; the timeout is a
            # backstop against a wedged engine, not a policy knob (use
            # deadline_ms for per-request deadlines)
            out = fut.result(timeout=600)
            resp = {
                "outputs": _to_jsonable(out),
                "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
            }
            if self._obs.trace_id:
                resp["trace_id"] = self._obs.trace_id
            self._reply(200, resp)
        except Exception as e:    # noqa: BLE001 — every error is a response
            self._error_reply(e, metrics=batcher.metrics)

    # ----------------------------------------------------- POST kv export

    def _post_kv_export(self):
        """Disaggregated-serving SOURCE side (serving/transfer.py;
        docs/serving.md "Disaggregated serving"): a peer decode replica
        asks for our longest resident KV coverage of a token prefix.
        The gather reads the committed (donated) cache, which belongs to
        the batcher worker thread, so the worker serializes the chain
        strictly BETWEEN decode steps (``GenerationBatcher.
        export_chain``); this handler only ships the resulting blob —
        8-byte little-endian length prefix + payload, bounded chunks."""
        from paddle_tpu.utils.flags import FLAGS
        gen = self.server.gen_batcher
        if gen is None:
            self._reply(404, {"error": "no generation plane on this "
                                       "replica: nothing to export"})
            return
        try:
            req = self._read_json()
            tokens = req.get("tokens")
            if not isinstance(tokens, list) or not tokens \
                    or not all(isinstance(t, int) for t in tokens):
                raise InvalidRequestError(
                    "'tokens' must be a non-empty list of int token ids")
        except Exception as e:   # noqa: BLE001 — every error is a response
            self._error_reply(e, metrics=gen.metrics)
            return
        key, covered, blob = gen.export_chain(
            tokens, timeout=FLAGS.serving_handoff_timeout_s)
        if blob is None:
            # no resident coverage (evicted, never prefilled here, or
            # the export timed out behind a wedged step): the peer falls
            # back to recompute — this 404 is an outcome, not a failure
            self._reply(404, {"error": "no resident KV coverage for the "
                                       "requested tokens"})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        # the length prefix travels INSIDE the body so the framing is
        # transport-independent; read_blob re-checks the declared length
        # against the receiver's own bound before buffering toward it
        self.send_header("Content-Length", str(8 + len(blob)))
        self.send_header("X-KV-Covered", str(int(covered)))
        if self._obs.trace_id:
            self.send_header("X-Trace-Id", self._obs.trace_id)
        self.end_headers()
        kv_transfer.write_blob(self.wfile, blob)
        gen.metrics.observe_kv_handoff("sent", len(blob))

    def _receive_handoff(self, gen, hint):
        """Disaggregated-serving RECEIVE side: the router attached a
        ``{"source": url, "tokens": [ids]}`` hint naming the prefill
        replica that holds this stream's KV.  Fetch + verify + park the
        chain in the host tier BEFORE admission, so the request's
        ordinary seat probe restore-hits it through the existing
        claim/stage/commit pipeline.  ANY failure — dead peer, foreign
        or oversized blob, the analytic model preferring recompute, a
        malformed hint — is the recompute fallback, never a client
        error."""
        from paddle_tpu.utils.flags import FLAGS
        if not FLAGS.serving_handoff:
            gen.metrics.observe_kv_handoff("fallback")
            return {"outcome": "fallback", "bytes": 0, "covered": 0,
                    "ms": 0.0, "reason": "disabled"}
        source = hint.get("source") if isinstance(hint, dict) else None
        tokens = hint.get("tokens") if isinstance(hint, dict) else None
        if not isinstance(source, str) \
                or not isinstance(tokens, list) or not tokens \
                or not all(isinstance(t, int) for t in tokens):
            gen.metrics.observe_kv_handoff("fallback")
            return {"outcome": "fallback", "bytes": 0, "covered": 0,
                    "ms": 0.0, "reason": "malformed_hint"}
        return kv_transfer.receive_chain(
            gen.engine, source, tokens, metrics=gen.metrics,
            max_bytes=FLAGS.serving_handoff_max_bytes,
            timeout=FLAGS.serving_handoff_timeout_s)

    # ------------------------------------------------------- POST generate

    def _post_generate(self):
        t0 = time.perf_counter()
        gen = self.server.gen_batcher
        if gen is None:
            self._reply(404, {"error": "no generation model is being "
                                       "served (start with "
                                       "--demo-generate or wire a "
                                       "GenerationBatcher)"})
            return
        try:
            req = self._read_json()
            if "prompt" not in req:
                raise InvalidRequestError('body must be {"prompt": [ids]}')
            prompt = req["prompt"]
            if not isinstance(prompt, list) or not prompt \
                    or not all(isinstance(t, int) for t in prompt):
                raise InvalidRequestError(
                    "'prompt' must be a non-empty list of int token ids")
            try:
                prompt = np.asarray(prompt, np.int64)
            except (OverflowError, ValueError) as e:
                # Python ints are unbounded; an id past int64 is a
                # malformed request, not a server error
                raise InvalidRequestError(
                    f"prompt ids out of range: {e}") from e
            deadline_ms = self._deadline_ms(req)
            replay = req.get("replay")
            if replay is not None:
                # mid-stream continuation (a router failing over off a
                # dead replica, docs/serving.md §7): these tokens were
                # already delivered — teacher-forced, never re-emitted
                if not isinstance(replay, list) or not replay \
                        or not all(isinstance(t, int) for t in replay):
                    raise InvalidRequestError(
                        "'replay' must be a non-empty list of int token "
                        "ids")
                try:
                    replay = np.asarray(replay, np.int64)
                except (OverflowError, ValueError) as e:
                    raise InvalidRequestError(
                        f"replay ids out of range: {e}") from e
            # disaggregated handoff (serving/transfer.py): pull the
            # stream's KV off the named prefill replica before admission
            handoff = None
            if req.get("kv_handoff") is not None:
                handoff = self._receive_handoff(gen, req["kv_handoff"])
            kw = dict(max_tokens=req.get("max_tokens"),
                      eos_id=req.get("eos_id"), deadline_ms=deadline_ms,
                      replay=replay)
            if req.get("stream"):
                self._generate_stream(gen, prompt, kw, t0, handoff=handoff)
                return
            out = self._submit_retrying(
                gen, lambda: gen.submit(prompt, **kw)).result(timeout=600)
            out = dict(out)
            if handoff is not None:
                out["kv_handoff"] = handoff
            out["latency_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
            if self._obs.trace_id:
                out["trace_id"] = self._obs.trace_id
            self._obs.set(ttft_ms=out.get("ttft_ms"))   # slowest(n) key
            self._reply(200, out)
        except Exception as e:    # noqa: BLE001 — every error is a response
            self._error_reply(e, metrics=gen.metrics)

    def _generate_stream(self, gen, prompt, kw, t0, handoff=None):
        """Chunked-transfer NDJSON stream: one {"token": id} record per
        emitted token (pushed from the decode loop as the slot advances),
        then a closing {"done": true, ...} record.  Admission errors are
        raised BEFORE any bytes go out, so they still map to their status
        codes; a failure mid-stream terminates with an {"error": ...}
        record instead (the status line is already on the wire)."""
        events = _queue.Queue()
        fut = self._submit_retrying(
            gen, lambda: gen.submit(
                prompt, on_token=lambda t: events.put(("token", t)), **kw))
        # the callback fires in the engine thread strictly before the
        # future resolves, so the queue orders tokens before done
        fut.add_done_callback(lambda f: events.put(("done", f)))
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            if self._obs.trace_id:
                self.send_header("X-Trace-Id", self._obs.trace_id)
            self.end_headers()
        except Exception as e:    # noqa: BLE001 — peer gone before the
            # status line finished: a second reply would corrupt the
            # connection; reclaim the slot and drop it
            logger.warning("generate stream: client gone before headers: "
                           "%s: %s", type(e).__name__, e)
            gen.abandon(fut)
            self.close_connection = True
            return

        def chunk(obj):
            data = (json.dumps(obj) + "\n").encode()
            self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")

        # the status line is on the wire: from here every failure must
        # terminate the chunk stream, never fall back to a second reply
        try:
            streamed = 0
            while True:
                kind, val = events.get(timeout=600)
                if kind == "token":
                    if streamed == 0:
                        self._obs.event("first_token")
                    streamed += 1
                    chunk({"token": int(val)})
                    continue
                exc = val.exception()
                if exc is not None:
                    chunk({"error": f"{type(exc).__name__}: {exc}"})
                else:
                    out = dict(val.result())
                    out["done"] = True
                    if handoff is not None:
                        out["kv_handoff"] = handoff
                    out["latency_ms"] = round(
                        (time.perf_counter() - t0) * 1e3, 3)
                    if self._obs.trace_id:
                        out["trace_id"] = self._obs.trace_id
                    self._obs.set(ttft_ms=out.get("ttft_ms"))
                    chunk(out)
                break
            self.wfile.write(b"0\r\n\r\n")
        except Exception as e:    # noqa: BLE001 — client gone / wedged
            logger.warning("generate stream aborted: %s: %s",
                           type(e).__name__, e)
            # the reader is gone: reclaim its decode slot instead of
            # generating to max_tokens for nobody
            gen.abandon(fut)
            # best-effort error record + terminator, then DROP the
            # connection: a keep-alive socket with an unterminated chunk
            # stream would block the client forever
            try:
                chunk({"error": f"stream aborted: {type(e).__name__}"})
                self.wfile.write(b"0\r\n\r\n")
            except Exception:   # noqa: BLE001 — socket already gone
                pass
            self.close_connection = True


def make_server(batcher, host="127.0.0.1", port=0, gen_batcher=None):
    """Bind (port 0 = ephemeral) and return the server; caller runs
    ``serve_forever()``.  ``server.port`` carries the bound port.

    batcher: the /v1/infer ``Batcher`` (None for a generation-only
    server); gen_batcher: the /v1/generate ``GenerationBatcher`` (None
    for an inference-only server).  At least one must be given."""
    if batcher is None and gen_batcher is None:
        raise ValueError("make_server needs a batcher, a gen_batcher, or "
                         "both")
    httpd = ThreadingHTTPServer((host, port), ServingHandler)
    httpd.daemon_threads = True
    httpd.batcher = batcher
    httpd.gen_batcher = gen_batcher
    httpd.port = httpd.server_address[1]
    # effective drain deadline (drives the ShutdownError Retry-After);
    # _serve overwrites it with the CLI's --drain-timeout-s
    httpd.drain_timeout_s = None
    return httpd


# ------------------------------------------------------------------- CLI


def _demo_engine(buckets, warm=True):
    """Built-in tiny MLP engine — bring-up/smoke without an artifact."""
    from paddle_tpu.layers import api as L
    from paddle_tpu.layers.graph import Topology, reset_names
    reset_names()
    x = L.data_layer("serving_demo_x", size=16)
    h = L.fc_layer(input=x, size=32, act="tanh")
    out = L.fc_layer(input=h, size=4, act="softmax")
    params = Topology([out]).init(jax.random.PRNGKey(0))
    spec = {"serving_demo_x": jax.ShapeDtypeStruct((1, 16), np.float32)}
    return InferenceEngine.from_topology(out, params, spec, buckets=buckets,
                                         warm=warm, name="demo")


def _demo_gen_batcher(args, tiny=False, metrics=None):
    """Built-in tiny decoder-only LM trunk behind the continuous-batching
    decode engine — /v1/generate bring-up and smoke without a trained
    model.  ``tiny=True`` shrinks slab + ladder to smoke scale so the
    self-test warms in seconds.  ``metrics``: share the inference
    batcher's ServingMetrics on a combined server, so /metrics reports
    BOTH planes from the one object the handler renders."""
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.decode_engine import (DecodeEngine,
                                                  GenerationBatcher)
    if tiny:
        slots, max_len, buckets = 4, 48, (8, 16)
    else:
        slots = args.gen_slots
        max_len = args.gen_max_len
        buckets = tuple(int(b) for b in args.gen_prefill_buckets.split(","))
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=256,
                              trg_vocab=1, d_model=32, num_heads=2,
                              dff=64, enc_layers=2, dec_layers=0,
                              max_len=max_len)
    if getattr(args, "quant_weights", False):
        # per-channel int8 trunk weights (quant/weights.py): the engine
        # and every step variant accept the quantized tree directly
        from paddle_tpu.quant.weights import quantize_lm
        params = quantize_lm(params)
    speculate_k = int(getattr(args, "speculate_k", 0) or 0)
    draft = None
    if speculate_k:
        # the draft shares the target's embedding/vocab — a quantized
        # target hands the draft its quantized tree, which every step
        # variant dequantizes in place
        from paddle_tpu.serving.speculative import make_draft
        draft = make_draft(params,
                           layers=getattr(args, "draft_layers", 1))
    mesh = None
    mesh_shards = int(getattr(args, "mesh_shards", 0) or 0)
    if mesh_shards > 1:
        # tensor-parallel decode (docs/serving.md "Sharded decode"):
        # the demo trunk's heads/vocab divide any power-of-two mesh
        from paddle_tpu.parallel import sharding as _psh
        mesh = _psh.decode_mesh(mesh_shards)
    engine = DecodeEngine(params, num_heads=2, num_slots=slots,
                          max_len=max_len, prefill_buckets=buckets,
                          name="demo_lm", metrics=metrics, mesh=mesh,
                          kv_layout=args.kv_layout,
                          kv_block_size=args.kv_block_size,
                          kv_num_blocks=args.kv_num_blocks,
                          prefix_cache=args.kv_prefix_cache,
                          kv_dtype=getattr(args, "kv_dtype", "float32"),
                          prefill_chunk=getattr(args, "prefill_chunk", 0),
                          prefill_chunk_budget=getattr(
                              args, "prefill_chunk_budget", 0),
                          speculate_k=speculate_k, draft=draft,
                          kv_host_bytes=getattr(args, "kv_host_bytes", 0))
    # supervision on by default for the generation plane: the breaker
    # and recovery are pure host bookkeeping (zero cost absent failures);
    # the step watchdog only arms when a deadline is configured
    sup = Supervisor(
        step_deadline_s=(args.step_deadline_ms / 1e3
                         if args.step_deadline_ms else None),
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown_s)
    return GenerationBatcher(engine, queue_size=args.queue_size,
                             default_deadline_ms=args.deadline_ms,
                             default_max_tokens=args.gen_max_tokens,
                             supervisor=sup)


def _build_engine(args):
    if args.artifact:
        return InferenceEngine.from_artifact(args.artifact)
    if args.artifacts:
        return InferenceEngine.from_artifacts(args.artifacts)
    if args.demo:
        buckets = tuple(int(b) for b in args.buckets.split(","))
        return _demo_engine(buckets)
    raise SystemExit("serving: pass one of --artifact PATH, "
                     "--artifacts GLOB, --demo, --demo-generate")


def _zeros_row_json(engine, fill=0.5):
    """A valid JSON feed for this engine's spec (smoke traffic)."""
    row = {}
    for name, sds in engine.bucket_spec(1).items():
        shape = tuple(sds.shape[1:])
        if np.issubdtype(sds.dtype, np.integer):
            row[name] = np.zeros(shape, sds.dtype).tolist()
        else:
            row[name] = np.full(shape, fill, sds.dtype).tolist()
    return row


def _smoke(batcher, n_requests=8):
    """Self-contained serving smoke: ephemeral port, n concurrent HTTP
    requests, a malformed request, /healthz + /metrics sanity.  Prints ONE
    JSON line; returns the process exit code (healthy_window.sh phase)."""
    import urllib.error
    import urllib.request

    httpd = make_server(batcher, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.port}"
    feed = _zeros_row_json(batcher.engine)
    ok = [0]
    errs = []

    def hit(i):
        body = json.dumps({"feed": feed}).encode()
        try:
            with urllib.request.urlopen(urllib.request.Request(
                    f"{base}/v1/infer", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=30) as r:
                resp = json.loads(r.read())
                if "outputs" in resp:
                    ok[0] += 1
        except Exception as e:    # noqa: BLE001
            errs.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=hit, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)

    # malformed JSON must 400 without wounding the engine
    bad_status = None
    try:
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/v1/infer", data=b"{not json",
            headers={"Content-Type": "application/json"}), timeout=30)
    except urllib.error.HTTPError as e:
        bad_status = e.code
    with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
        health = json.loads(r.read())
    # readiness split (§5): a warm, serving, non-draining node is ready
    with urllib.request.urlopen(f"{base}/readyz", timeout=30) as r:
        ready = json.loads(r.read())
    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
        metrics_text = r.read().decode()

    snap = batcher.metrics.snapshot()
    name = batcher.metrics.name
    metrics_sane = (
        f"{name}_requests_total {snap['requests_total']}" in metrics_text
        and f"{name}_batches_total" in metrics_text
        and 'latency_seconds{quantile="0.50"}' in metrics_text
        and snap["responses_total"] == ok[0]
        and snap["batches_total"] >= 1)
    out = {
        "metric": "serving smoke (dynamic batcher + HTTP front-end)",
        "value": ok[0], "unit": f"requests_ok/{n_requests}",
        "vs_baseline": None,
        "bad_request_status": bad_status,
        "healthz": health.get("status"),
        "readyz": ready.get("status"),
        "metrics_sane": bool(metrics_sane),
        "mean_occupancy": snap["mean_occupancy"],
        "p50_ms": snap["latency_ms"]["p50"],
        "p99_ms": snap["latency_ms"]["p99"],
    }
    if errs:
        out["errors"] = errs[:5]
    httpd.shutdown()
    batcher.close()
    print(json.dumps(out), flush=True)
    passed = (ok[0] == n_requests and bad_status == 400
              and health.get("status") == "ok"
              and ready.get("status") == "ready" and metrics_sane)
    return 0 if passed else 2


def _smoke_generate(gen, n_requests=6):
    """Generation-serving self-test (healthy_window.sh phase 8): ephemeral
    port, concurrent STAGGERED /v1/generate requests with mixed prompt
    lengths and max_tokens (so admissions land mid-decode and slots churn),
    one streaming request, and an EOS early-finish probe (greedy decode is
    deterministic: replaying a prompt with eos_id set to one of its own
    continuation tokens must finish early with reason "eos").  Prints ONE
    JSON line; returns the process exit code."""
    import urllib.request

    httpd = make_server(None, port=0, gen_batcher=gen)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.port}"
    rng = np.random.RandomState(0)
    results = [None] * n_requests
    errs = []

    def post(body):
        req = urllib.request.Request(
            f"{base}/v1/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, r.read()

    def hit(i):
        prompt = rng.randint(1, 256, 3 + 2 * i).tolist()
        n_tok = 10 + 3 * (i % 3)
        try:
            time.sleep(0.005 * i)       # staggered admissions: later
            # requests land while earlier ones are mid-decode, so slots
            # churn (admission between steps, never a retrace)
            status, raw = post({"prompt": prompt, "max_tokens": n_tok})
            resp = json.loads(raw)
            if status == 200 and len(resp["tokens"]) == n_tok \
                    and resp["finish_reason"] == "length":
                results[i] = resp
        except Exception as e:    # noqa: BLE001
            errs.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=hit, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    ok = sum(1 for r in results if r is not None)

    # streaming: chunked NDJSON — tokens then a done record, and the
    # streamed ids must equal the non-streamed result for the same prompt
    # (greedy decode is deterministic).  EOS probe: replay stops AT the
    # first occurrence of the chosen stop token.  Guarded like hit(): a
    # probe failure must become a False flag in the ONE JSON line, never
    # a traceback that leaves phase 8's artifact empty.
    stream_ok = eos_ok = False
    try:
        probe = rng.randint(1, 256, 5).tolist()
        _, raw = post({"prompt": probe, "max_tokens": 6})
        plain = json.loads(raw)
        _, raw = post({"prompt": probe, "max_tokens": 6, "stream": True})
        lines = [json.loads(ln) for ln in raw.decode().splitlines() if ln]
        streamed = [ln["token"] for ln in lines if "token" in ln]
        done = [ln for ln in lines if ln.get("done")]
        stream_ok = (bool(done) and streamed == plain["tokens"]
                     and done[0]["tokens"] == plain["tokens"])
        eos = plain["tokens"][2]
        _, raw = post({"prompt": probe, "max_tokens": 6, "eos_id": eos})
        eos_probe = json.loads(raw)
        eos_ok = (eos_probe["finish_reason"] == "eos"
                  and eos_probe["tokens"][-1] == eos
                  and len(eos_probe["tokens"]) <= 3)
    except Exception as e:    # noqa: BLE001
        errs.append(f"probe: {type(e).__name__}: {e}")

    with urllib.request.urlopen(f"{base}/readyz", timeout=30) as r:
        ready = json.loads(r.read())
    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
        metrics_text = r.read().decode()
    snap = gen.metrics.snapshot()
    name = gen.metrics.name
    metrics_sane = (
        f"{name}_gen_tokens_total {snap['gen_tokens_total']}" in metrics_text
        and f"{name}_decode_steps_total" in metrics_text
        and 'ttft_seconds{quantile="0.50"}' in metrics_text
        and snap["gen_tokens_total"] > 0
        and snap["decode_steps_total"] > 0)
    out = {
        "metric": "generation serving smoke (continuous batching + HTTP)",
        "value": ok, "unit": f"requests_ok/{n_requests}",
        "vs_baseline": None,
        "stream_ok": bool(stream_ok),
        "eos_early_finish": bool(eos_ok),
        "readyz": ready.get("status"),
        "metrics_sane": bool(metrics_sane),
        "mean_slot_occupancy": snap["mean_slot_occupancy"],
        "gen_tokens_total": snap["gen_tokens_total"],
        "evictions": snap["evictions"],
        "ttft_p50_ms": snap["ttft_ms"]["p50"],
        "tpot_p50_ms": snap["tpot_ms"]["p50"],
    }
    if errs:
        out["errors"] = errs[:5]
    httpd.shutdown()
    gen.close()
    print(json.dumps(out), flush=True)
    passed = (ok == n_requests and stream_ok and eos_ok and metrics_sane
              and ready.get("status") == "ready")
    return 0 if passed else 2


def _smoke_paged(args):
    """Paged-KV-cache self-test (healthy_window.sh phase 11; docs/
    serving.md §5): serve the demo LM with ``kv_layout="paged"`` on an
    ephemeral port and drive the prefix-sharing scenario — one client
    establishes a long system-prompt context (prefix-cache miss, chains
    registered), then two clients sharing that system prompt (one the
    EXACT prompt — its seat lands inside the shared tail block and must
    copy-on-write fork it — one with a divergent question) admit by
    reference.  Every stream must be bit-identical to the SAME prompts
    served through a slab-layout twin engine (greedy decode — one
    compiled trunk, two memory layouts, same tokens), /metrics must
    show the hits, the fork, and the block-pool gauges.  Prints ONE
    JSON line; returns the process exit code."""
    import copy
    import urllib.request

    paged_args = copy.copy(args)
    paged_args.kv_layout = "paged"
    paged_args.kv_block_size = min(args.kv_block_size, 8)
    gen = _demo_gen_batcher(paged_args, tiny=True)
    slab_args = copy.copy(args)
    slab_args.kv_layout = "slab"
    slab = _demo_gen_batcher(slab_args, tiny=True)

    httpd = make_server(None, port=0, gen_batcher=gen)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.port}"
    bs = gen.engine.block_size
    rng = np.random.RandomState(0)
    # system prompt spanning one full block + a partial tail; questions
    # keep the total inside the tiny prefill ladder (top bucket 16)
    sys_prompt = rng.randint(1, 256, bs + bs // 2).tolist()
    qa = rng.randint(1, 256, 4).tolist()
    qb = rng.randint(1, 256, 4).tolist()
    prompts = [sys_prompt + qa,         # leader: miss, registers chains
               sys_prompt + qa,         # exact dup: hit + CoW fork
               sys_prompt + qb]         # divergent: shared-prefix hit
    n_tok = 8
    errs = []

    def post(body):
        req = urllib.request.Request(
            f"{base}/v1/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, r.read()

    def generate(i, stream):
        try:
            if stream:
                _, raw = post({"prompt": prompts[i], "max_tokens": n_tok,
                               "stream": True})
                lines = [json.loads(ln) for ln in raw.decode().splitlines()
                         if ln]
                done = [ln for ln in lines if ln.get("done")]
                toks = [ln["token"] for ln in lines if "token" in ln]
                if not done or done[0]["tokens"] != toks:
                    errs.append(f"client {i}: stream/done mismatch")
                    return None
                return toks
            status, raw = post({"prompt": prompts[i],
                                "max_tokens": n_tok})
            resp = json.loads(raw)
            if status != 200 or resp["finish_reason"] != "length":
                errs.append(f"client {i}: {status} {resp}")
                return None
            return resp["tokens"]
        except Exception as e:    # noqa: BLE001 — a probe failure must
            # become a False flag in the ONE JSON line, never a traceback
            errs.append(f"client {i}: {type(e).__name__}: {e}")
            return None

    results = [None] * len(prompts)
    results[0] = generate(0, stream=False)      # leader registers first
    follower_threads = [
        threading.Thread(target=lambda i=i: results.__setitem__(
            i, generate(i, stream=i == 1)))
        for i in range(1, len(prompts))]
    for t in follower_threads:
        t.start()
    for t in follower_threads:
        t.join(120)
    ok = sum(1 for r in results if r is not None)

    # the slab twin serves the same prompts; greedy decode must agree
    # token for token across the two memory layouts
    bit_identical = False
    try:
        ref = [slab.submit(np.asarray(p, np.int64),
                           max_tokens=n_tok).result(120)["tokens"]
               for p in prompts]
        bit_identical = all(r is not None and r == e
                            for r, e in zip(results, ref))
    except Exception as e:    # noqa: BLE001
        errs.append(f"slab twin: {type(e).__name__}: {e}")

    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
        metrics_text = r.read().decode()
    snap = gen.metrics.snapshot()
    name = gen.metrics.name
    metrics_sane = (
        f"{name}_prefix_cache_hits_total "
        f"{snap['prefix_cache_hits_total']}" in metrics_text
        and f"{name}_cow_forks_total {snap['cow_forks_total']}"
        in metrics_text
        and f"{name}_kv_blocks_total {snap['kv_blocks_total']}"
        in metrics_text
        and snap["kv_blocks_total"] > 0)
    out = {
        "metric": "paged KV serving smoke (prefix sharing + CoW + HTTP)",
        "value": ok, "unit": f"requests_ok/{len(prompts)}",
        "vs_baseline": None,
        "bit_identical": bool(bit_identical),
        "prefix_cache_hits": snap["prefix_cache_hits_total"],
        "prefix_cache_misses": snap["prefix_cache_misses_total"],
        "cow_forks": snap["cow_forks_total"],
        "kv_blocks_total": snap["kv_blocks_total"],
        "kv_blocks_used": snap["kv_blocks_used"],
        "pool_exhausted_evictions": snap["evictions"]["pool_exhausted"],
        "prefill_positions": gen.engine.prefill_positions_total,
        "metrics_sane": bool(metrics_sane),
    }
    if errs:
        out["errors"] = errs[:5]
    httpd.shutdown()
    gen.close()
    slab.close()
    print(json.dumps(out), flush=True)
    passed = (ok == len(prompts) and bit_identical and metrics_sane
              and snap["prefix_cache_hits_total"] >= 2
              and snap["cow_forks_total"] >= 1)
    return 0 if passed else 2


def _smoke_spill(args):
    """Hierarchical-KV self-test (healthy_window.sh phase 20; docs/
    serving.md "Hierarchical KV"): serve the demo LM with a tiny paged
    pool plus a host-RAM spill tier on an ephemeral port.  A leader
    establishes a long block-aligned system-prompt context, churn
    traffic forces the pool to evict (and therefore spill) that chain,
    and then the leader's prompt RETURNS: the engine must restore-hit
    from the host tier and seat by reference — ZERO prefill chunk lanes
    for the covered prefix — with the stream bit-identical both to the
    first serving and to a tier-less twin's cold recompute.  /metrics
    must show the spill/restore counters and the host-tier gauge.
    Prints ONE JSON line; returns the process exit code."""
    import copy
    import urllib.request

    bs = 8
    spill_args = copy.copy(args)
    spill_args.kv_layout = "paged"
    spill_args.kv_block_size = bs
    # two slots' worth of blocks + 1: the shared chain cannot stay
    # resident once churn traffic claims seats
    spill_args.kv_num_blocks = 2 * (48 // bs) + 1
    spill_args.kv_prefix_cache = True
    spill_args.prefill_chunk = bs
    spill_args.kv_host_bytes = 64 << 20
    gen = _demo_gen_batcher(spill_args, tiny=True)
    twin_args = copy.copy(spill_args)
    twin_args.kv_host_bytes = 0
    twin = _demo_gen_batcher(twin_args, tiny=True)

    httpd = make_server(None, port=0, gen_batcher=gen)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.port}"
    rng = np.random.RandomState(0)
    # block-aligned system prompt: the registered chain covers every
    # prompt position, so the return visit needs no chunk lanes at all
    sys_prompt = rng.randint(1, 256, 4 * bs).tolist()
    churn = [rng.randint(1, 256, 28).tolist() for _ in range(4)]
    n_tok = 6
    errs = []

    def post(prompt):
        req = urllib.request.Request(
            f"{base}/v1/generate",
            data=json.dumps({"prompt": prompt,
                             "max_tokens": n_tok}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                resp = json.loads(r.read())
                if r.status != 200 or resp["finish_reason"] != "length":
                    errs.append(f"{r.status} {resp}")
                    return None
                return resp["tokens"]
        except Exception as e:    # noqa: BLE001 — a probe failure must
            # become a False flag in the ONE JSON line, not a traceback
            errs.append(f"{type(e).__name__}: {e}")
            return None

    first = post(sys_prompt)                    # miss: registers chains
    for p in churn:                             # pool pressure -> spill
        post(p)
    snap_mid = gen.metrics.snapshot()
    lanes_before = snap_mid["prefill_chunk_lanes_total"]
    returned = post(sys_prompt)                 # must restore-hit
    snap = gen.metrics.snapshot()
    lanes_return = snap["prefill_chunk_lanes_total"] - lanes_before

    bit_identical = False
    try:
        ref = twin.submit(np.asarray(sys_prompt, np.int64),
                          max_tokens=n_tok).result(120)["tokens"]
        bit_identical = (first is not None and first == returned
                         and returned == ref)
    except Exception as e:    # noqa: BLE001
        errs.append(f"twin: {type(e).__name__}: {e}")

    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
        metrics_text = r.read().decode()
    name = gen.metrics.name
    metrics_sane = (
        f"{name}_kv_restore_hits_total "
        f"{snap['kv_restore_hits_total']}" in metrics_text
        and f"{name}_kv_spill_blocks_total "
            f"{snap['kv_spill_blocks_total']}" in metrics_text
        and f"{name}_host_tier_bytes" in metrics_text
        and f"{name}_kv_restore_seconds_count" in metrics_text)
    out = {
        "metric": "hierarchical KV smoke (spill + async restore + HTTP)",
        "value": snap["kv_restore_hits_total"], "unit": "restore_hits",
        "vs_baseline": None,
        "bit_identical": bool(bit_identical),
        "kv_spill_blocks": snap["kv_spill_blocks_total"],
        "kv_restore_hits": snap["kv_restore_hits_total"],
        "kv_restore_bytes": snap["kv_restore_bytes_total"],
        "kv_restore_ms": snap["kv_restore_ms"],
        "host_tier_bytes": snap["host_tier_bytes"],
        "chunk_lanes_return_visit": lanes_return,
        "step_traces": gen.engine.step_trace_count,
        "metrics_sane": bool(metrics_sane),
    }
    if errs:
        out["errors"] = errs[:5]
    httpd.shutdown()
    gen.close()
    twin.close()
    print(json.dumps(out), flush=True)
    passed = (bit_identical and metrics_sane
              and snap["kv_spill_blocks_total"] > 0
              and snap["kv_restore_hits_total"] >= 1
              and lanes_return == 0
              and gen.engine.step_trace_count == 1)
    return 0 if passed else 2


def _smoke_decode_fused(args):
    """Fused decode-kernel self-test (healthy_window.sh phase 13;
    docs/perf.md "Fused decode kernels"): the demo generation drive with
    ``pallas_decode=always`` — the Pallas decode-attention kernels
    compiled INTO the slab and paged steps (interpret mode on CPU, the
    real Mosaic kernels on TPU) — against a reference-path twin engine
    serving the same staggered prompts.  Every greedy stream must be
    bit-identical between the two steps, both fused engines must hold
    the 1-warm-up-trace/0-retrace discipline across the churn, and both
    kernels (slab + paged) must actually have engaged (engine
    ``decode_kernels`` resolution).  Prints ONE JSON line; returns the
    process exit code."""
    import copy

    from paddle_tpu.ops.pallas import decode_attention as decode_kernels

    rng = np.random.RandomState(0)
    n_tok = 8
    prompts = [rng.randint(1, 256, rng.randint(3, 17)).astype(np.int64)
               for _ in range(6)]
    errs = []
    out = {"metric": "fused decode-kernel smoke (pallas_decode vs "
                     "reference twin)",
           "vs_baseline": None}
    ok_layouts = 0
    for layout in ("slab", "paged"):
        a = copy.copy(args)
        a.kv_layout = layout
        a.kv_block_size = min(args.kv_block_size, 8)
        with decode_kernels.forced_mode("always"):
            fused = _demo_gen_batcher(a, tiny=True)
        # the twin must force the kernels OFF: on TPU the default
        # "auto" would fuse it too and the comparison would be
        # fused-vs-fused
        with decode_kernels.forced_mode("off"):
            ref = _demo_gen_batcher(a, tiny=True)
        engaged = bool(fused.engine.decode_kernels)
        traces0 = fused.engine.step_trace_count

        def drive(bat):
            futs, res = [], []
            for i, p in enumerate(prompts):
                futs.append(bat.submit(p, max_tokens=n_tok))
                if i % 2:
                    time.sleep(0.01)    # staggered: admissions land
                    #                     mid-decode, slots churn
            for f in futs:
                res.append(f.result(120)["tokens"])
            return res

        try:
            got = drive(fused)
            want = drive(ref)
            identical = got == want
        except Exception as e:  # noqa: BLE001 — a drive failure must
            # become a False flag in the ONE JSON line, not a traceback
            errs.append(f"{layout}: {type(e).__name__}: {e}")
            identical = False
        retraced = fused.engine.step_trace_count - traces0
        fused.close()
        ref.close()
        out[f"{layout}_kernel_engaged"] = engaged
        out[f"{layout}_bit_identical"] = bool(identical)
        out[f"{layout}_retraces"] = int(retraced)
        if engaged and identical and retraced == 0:
            ok_layouts += 1
    out["value"] = ok_layouts
    out["unit"] = "layouts_ok/2"
    if errs:
        out["errors"] = errs[:5]
    print(json.dumps(out), flush=True)
    return 0 if ok_layouts == 2 else 2


def _smoke_chunked(args):
    """Chunked-prefill self-test (healthy_window.sh phase 15; docs/
    serving.md "Chunked prefill"): the demo LM with prompt ingestion
    folded into the unified decode step.  A short stream is put
    mid-decode, then a LONG prompt (the legacy ladder's whole top
    bucket) is admitted: its ingestion must ride the step as chunks
    (``prefill_chunks_total``), the in-flight stream must KEEP EMITTING
    between the newcomer's submit and its first token (the TPOT-
    bounding property the legacy ladder lacks — its monolithic prefill
    stalls every in-flight row), and every stream must come back
    bit-identical to the same prompts served through a legacy-ladder
    twin engine (one compiled trunk, two ingestion modes, same greedy
    tokens).  Prints ONE JSON line; returns the process exit code."""
    import copy

    chunk_args = copy.copy(args)
    chunk_args.prefill_chunk = min(4, args.prefill_chunk or 4) or 4
    gen = _demo_gen_batcher(chunk_args, tiny=True)
    ladder_args = copy.copy(args)
    ladder_args.prefill_chunk = 0
    ladder = _demo_gen_batcher(ladder_args, tiny=True)
    kk = gen.engine.prefill_chunk
    rng = np.random.RandomState(0)
    short = rng.randint(1, 256, 4).astype(np.int64)
    long_p = rng.randint(1, 256, 16).astype(np.int64)  # tiny ladder top
    n_short, n_long = 40, 6
    errs = []
    a_tokens = []               # appended on the worker thread, so the
    #                             counts below are step-ordered, not
    #                             wall-clock-dependent
    a_count_at_b = [None]
    out = {"metric": "chunked-prefill smoke (unified step vs legacy "
                     "ladder twin)", "vs_baseline": None,
           "prefill_chunk": kk}
    try:
        fut_a = gen.submit(short, max_tokens=n_short,
                           on_token=lambda _t:
                           a_tokens.append(time.perf_counter()))
        deadline = time.perf_counter() + 60
        while not a_tokens and time.perf_counter() < deadline:
            time.sleep(0.002)       # put A provably mid-decode
        a_count_submit = len(a_tokens)
        fut_b = gen.submit(long_p, max_tokens=n_long,
                           on_token=lambda _t, s=a_count_at_b:
                           s.__setitem__(0, s[0] if s[0] is not None
                                         else len(a_tokens)))
        res_b = fut_b.result(120)
        res_a = fut_a.result(120)
        # decode tokens A emitted between B's submit and B's first token
        # — every one delivered WHILE B's prompt was chunking through
        # the shared step (both counters advance on the worker thread)
        interleaved = max(0, (a_count_at_b[0] or 0) - a_count_submit)
        ref_a = ladder.submit(short, max_tokens=n_short).result(120)
        ref_b = ladder.submit(long_p, max_tokens=n_long).result(120)
        bit_identical = (res_a["tokens"] == ref_a["tokens"]
                         and res_b["tokens"] == ref_b["tokens"])
        requests_ok = 2
    except Exception as e:      # noqa: BLE001 — a probe failure must
        # become a failed flag in the ONE JSON line, not a traceback
        errs.append(f"{type(e).__name__}: {e}")
        requests_ok, interleaved, bit_identical = 0, 0, False
    snap = gen.metrics.snapshot()
    min_chunks = -(-int(long_p.size - 1) // max(1, kk - 1))
    out.update({
        "value": requests_ok, "unit": "requests_ok/2",
        "bit_identical": bool(bit_identical),
        # decode tokens the in-flight stream received while the long
        # prompt was being ingested — the ladder's monolithic prefill
        # yields 0 here by construction
        "interleaved_tokens": int(interleaved),
        "prefill_chunks_total": snap["prefill_chunks_total"],
        "prefill_chunk_lanes_total": snap["prefill_chunk_lanes_total"],
        "mean_prefill_chunk_occupancy":
            snap["mean_prefill_chunk_occupancy"],
        "tpot_jitter_p99_p50": snap["tpot_jitter_p99_p50"],
        "ttft_long_ms": snap["ttft_ms"]["p99"],
    })
    if errs:
        out["errors"] = errs[:5]
    gen.close()
    ladder.close()
    print(json.dumps(out), flush=True)
    passed = (requests_ok == 2 and bit_identical and interleaved >= 1
              and snap["prefill_chunks_total"] >= min_chunks)
    return 0 if passed else 2


def _smoke_quant(args):
    """Quantized-serving self-test (healthy_window.sh phase 16; docs/
    serving.md "Quantized serving"): the demo LM behind an INT8-KV
    paged engine (kv_num_blocks auto-DOUBLED at the slab-equivalent
    byte budget) serving HTTP /v1/generate, its streams compared
    against a fp32-twin engine under the COMMITTED quality budget
    (quant/kv.py: every stream's common prefix >= GREEDY_PREFIX_MIN_FULL
    and at least half the streams token-exact — the demo trunk is a
    random-init babbler with near-tied logits, so the budget, not
    bit-identity, is the fp32 contract).  An int8-KV + int8-WEIGHT
    engine must additionally reproduce the QUANTIZED ``lm_generate``
    oracle token-EXACTLY — inside one quantization mode greedy decode
    stays fully deterministic, so the engine/oracle bit-identity
    discipline carries over unchanged.  /metrics must show
    ``kv_blocks_total`` exactly DOUBLE the fp32 twin's at equal pool
    bytes and ``kv_cache_int8 1``.  Prints ONE JSON line; returns the
    process exit code."""
    import copy
    import urllib.request

    from paddle_tpu.quant.kv import (GREEDY_PREFIX_MIN_FULL,
                                     greedy_prefix_len)

    i8_args = copy.copy(args)
    i8_args.kv_layout = "paged"
    i8_args.kv_block_size = min(args.kv_block_size, 8)
    i8_args.kv_num_blocks = 0           # auto: slab-equivalent bytes
    i8_args.kv_dtype = "int8"
    gen = _demo_gen_batcher(i8_args, tiny=True)
    f32_args = copy.copy(i8_args)
    f32_args.kv_dtype = "float32"
    twin = _demo_gen_batcher(f32_args, tiny=True)
    full_args = copy.copy(i8_args)
    full_args.quant_weights = True
    full = _demo_gen_batcher(full_args, tiny=True)

    httpd = make_server(None, port=0, gen_batcher=gen)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.port}"
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, rng.randint(3, 15)).tolist()
               for _ in range(6)]
    n_tok = 10
    errs = []

    def post(body):
        req = urllib.request.Request(
            f"{base}/v1/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def hit(i, out):
        try:
            time.sleep(0.005 * i)       # staggered: slots churn
            out[i] = post({"prompt": prompts[i],
                           "max_tokens": n_tok})["tokens"]
        except Exception as e:    # noqa: BLE001 — a probe failure must
            errs.append(f"client {i}: {type(e).__name__}: {e}")

    results = [None] * len(prompts)
    threads = [threading.Thread(target=hit, args=(i, results))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    ok = sum(1 for r in results if r is not None)

    within_budget = exact = full_exact = 0
    try:
        from paddle_tpu.models import transformer
        for i, p in enumerate(prompts):
            want = twin.submit(np.asarray(p, np.int64),
                               max_tokens=n_tok).result(120)["tokens"]
            pre = greedy_prefix_len(results[i], want)
            within_budget += int(pre >= min(GREEDY_PREFIX_MIN_FULL,
                                            n_tok))
            exact += int(results[i] == want)
            # full-quant engine vs the QUANTIZED lm_generate oracle:
            # token-exact (bit-identity inside the int8 mode)
            fgot = full.submit(np.asarray(p, np.int64),
                               max_tokens=n_tok).result(120)["tokens"]
            arr = np.asarray(p, np.int32)[None]
            oracle = np.asarray(transformer.lm_generate(
                full.engine.params, arr, arr.size + n_tok, num_heads=2,
                kv_dtype="int8"))[0, arr.size:].tolist()
            full_exact += int(fgot == oracle)
    except Exception as e:    # noqa: BLE001
        errs.append(f"twin: {type(e).__name__}: {e}")

    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
        metrics_text = r.read().decode()
    snap = gen.metrics.snapshot()
    twin_blocks = twin.engine._paged.pool.num_allocatable
    name = gen.metrics.name
    metrics_sane = (
        f"{name}_kv_blocks_total {snap['kv_blocks_total']}"
        in metrics_text
        and f"{name}_kv_cache_int8 1" in metrics_text
        and snap["kv_dtype"] == "int8")
    blocks_doubled = snap["kv_blocks_total"] == 2 * twin_blocks
    out = {
        "metric": "quantized serving smoke (int8 KV + int8 weights vs "
                  "fp32 twin)",
        "value": ok, "unit": f"requests_ok/{len(prompts)}",
        "vs_baseline": None,
        "within_budget": within_budget,
        "token_exact": exact,
        "full_quant_oracle_exact": full_exact,
        "kv_blocks_total": snap["kv_blocks_total"],
        "f32_twin_blocks": twin_blocks,
        "kv_blocks_doubled": bool(blocks_doubled),
        "kv_dtype": snap["kv_dtype"],
        "metrics_sane": bool(metrics_sane),
    }
    if errs:
        out["errors"] = errs[:5]
    httpd.shutdown()
    gen.close()
    twin.close()
    full.close()
    print(json.dumps(out), flush=True)
    passed = (ok == len(prompts) and blocks_doubled and metrics_sane
              and within_budget == len(prompts)
              and full_exact == len(prompts)
              and exact * 2 >= len(prompts))
    return 0 if passed else 2


def _smoke_quant_prefill(args):
    """End-to-end low-precision self-test (healthy_window.sh phase 22;
    docs/perf.md "Int8 flash prefill" / "Int8 weight-streaming
    trainer").  Serving half: the demo trunk's batched causal prefill
    with ``kv_dtype="int8"`` THROUGH the int8 flash kernel
    (``pallas_prefill_quant=always`` — interpret mode off-TPU), its
    logits bounded against the fp32 prefill twin by the COMMITTED
    budget (quant/kv.logit_err vs LOGIT_ERR_BUDGET, the one comparison
    every quant surface shares), and the kernel-written cache checked
    against Tp sequential ``lm_decode_step`` calls — int8 codes
    BIT-EQUAL, f32 scale sidecars to float-epsilon (layer N>0's scales
    see layer N-1's kernel output, which is reference-equal only to
    ~1e-7; tests/test_flash_quant.py holds the per-layer bit-exact
    claim).  Training half: a 3-step int8 weight-streaming trainer
    (``SGD(quant_weights=True)``) must track its f32 twin's per-step
    cost within quant/weights.TRAIN_LOSS_BUDGET with a non-empty int8
    twin tree.  Prints ONE JSON line; returns the process exit code."""
    import importlib

    from paddle_tpu.models import transformer
    from paddle_tpu.quant import kv as quant_kv
    from paddle_tpu.quant import weights as quant_weights
    flash = importlib.import_module(
        "paddle_tpu.ops.pallas.flash_attention")

    b, tp, max_len, heads, vocab = 4, 16, 48, 2, 256
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=vocab,
                              trg_vocab=1, d_model=32, num_heads=heads,
                              dff=64, enc_layers=2, dec_layers=0,
                              max_len=max_len)
    rng = np.random.RandomState(0)
    tokens = jax.numpy.asarray(rng.randint(1, vocab, (b, tp)),
                               jax.numpy.int32)
    errs = []

    # ---- int8 flash prefill vs the fp32 twin (eager: the bit-exact
    # contract is defined eagerly; whole-program jit may reassociate
    # the scale divide by 1 ulp on any path — tests/test_flash_quant.py)
    with flash.forced_prefill_quant_mode("always"):
        h8, cache8 = transformer.lm_prefill(params, tokens, max_len,
                                            heads, kv_dtype="int8")
    h32, _ = transformer.lm_prefill(params, tokens, max_len, heads)
    l8 = transformer._lm_project(params, h8)
    l32 = transformer._lm_project(params, h32)
    per_stream = quant_kv.logit_err(l32, l8)
    max_err = float(per_stream.max())
    in_budget = int((np.asarray(per_stream)
                     <= quant_kv.LOGIT_ERR_BUDGET).sum())

    # the kernel-fed cache vs Tp sequential decode steps: bit-equal
    cache_seq = transformer.init_lm_cache(params, b, max_len,
                                          kv_dtype="int8",
                                          num_heads=heads)
    for t in range(tp):
        _lg, cache_seq = transformer.lm_decode_step(
            params, tokens[:, t], t, cache_seq, num_heads=heads)
    cache_exact = all(
        bool(np.array_equal(np.asarray(l8_[k])[:, :tp],
                            np.asarray(ls[k])[:, :tp]))
        for l8_, ls in zip(cache8, cache_seq)
        for k in ("k", "v")) and all(
        bool(np.allclose(np.asarray(l8_[k])[:, :tp],
                         np.asarray(ls[k])[:, :tp], rtol=1e-6, atol=0))
        for l8_, ls in zip(cache8, cache_seq)
        for k in ("ks", "vs"))

    # ---- int8 weight-streaming trainer: 3-step loss parity ----------
    import paddle_tpu.optim as optim
    from paddle_tpu.data import DataFeeder, dense_vector, integer_value
    from paddle_tpu.layers import api as L
    from paddle_tpu.layers.graph import reset_names
    from paddle_tpu.trainer.trainer import SGD

    def build(quant):
        reset_names()
        x = L.data_layer("qp_x", size=4)
        lab = L.data_layer("qp_lab", size=1)
        h = L.fc_layer(input=x, size=16, act="tanh")
        y = L.fc_layer(input=h, size=2, act="softmax")
        cost = L.classification_cost(y, lab)
        return SGD(cost=cost,
                   update_equation=optim.Momentum(learning_rate=0.1,
                                                  momentum=0.9),
                   seed=7, quant_weights=quant, quant_min_size=16)

    loss_gap = qtree_leaves = -1
    try:
        tq, tf = build(True), build(False)
        qtree_leaves = len(tq._qtree)
        feeder = DataFeeder({"qp_x": dense_vector(4),
                             "qp_lab": integer_value(2)})
        trng = np.random.RandomState(1)
        loss_gap = 0.0
        for _ in range(3):
            xs = trng.randn(8, 4).astype(np.float32)
            ys = (xs[:, 0] > 0).astype(np.int64)
            batch = [(xs[j], int(ys[j])) for j in range(8)]
            cq = float(tq.train_one_batch(batch, feeder))
            cf = float(tf.train_one_batch(batch, feeder))
            loss_gap = max(loss_gap, abs(cq - cf) / max(abs(cf), 1.0))
    except Exception as e:    # noqa: BLE001 — the probe must report
        errs.append(f"trainer: {type(e).__name__}: {e}")

    out = {
        "metric": "quantized prefill + int8 trainer smoke (int8 flash "
                  "prefill vs fp32 twin; quant trainer vs f32 twin)",
        "value": in_budget, "unit": f"streams_in_budget/{b}",
        "vs_baseline": None,
        "max_logit_err": round(max_err, 4),
        "logit_err_budget": quant_kv.LOGIT_ERR_BUDGET,
        "cache_matches_sequential": bool(cache_exact),
        "trainer_loss_gap_max": (round(loss_gap, 5)
                                 if loss_gap >= 0 else None),
        "train_loss_budget": quant_weights.TRAIN_LOSS_BUDGET,
        "quant_tree_leaves": qtree_leaves,
    }
    if errs:
        out["errors"] = errs[:5]
    print(json.dumps(out), flush=True)
    passed = (not errs and in_budget == b and cache_exact
              and 0 <= loss_gap <= quant_weights.TRAIN_LOSS_BUDGET
              and qtree_leaves >= 2)
    return 0 if passed else 2


def _smoke_speculative(args):
    """Speculative-decoding self-test (healthy_window.sh phase 18;
    docs/serving.md "Speculative decoding"): the demo LM behind a
    speculating engine (1-layer draft riding the chunked step) serving
    concurrent staggered clients, every stream compared byte-for-byte
    against a NON-speculating twin of the same trunk — the draft may
    only ever change speed.  Acceptance evidence must land on the
    /metrics surface (drafted/accepted counters + the derived
    acceptance rate the snapshot carries), and both engines must hold
    the one-warm-up-trace discipline under acceptance churn.  Prints
    ONE JSON line; returns the process exit code."""
    import copy
    import threading

    spec_args = copy.copy(args)
    spec_args.prefill_chunk = min(4, args.prefill_chunk or 4) or 4
    spec_args.speculate_k = max(1, getattr(args, "speculate_k", 0) or 3)
    spec_args.draft_layers = max(1, getattr(args, "draft_layers", 1) or 1)
    gen = _demo_gen_batcher(spec_args, tiny=True)
    twin_args = copy.copy(spec_args)
    twin_args.speculate_k = 0
    twin = _demo_gen_batcher(twin_args, tiny=True)
    rng = np.random.RandomState(0)
    cases = [(rng.randint(1, 256, int(n)).astype(np.int64), int(m))
             for n, m in ((4, 12), (9, 8), (3, 14), (12, 10))]
    errs, results, ref = [], [None] * len(cases), [None] * len(cases)
    trace_spec = (gen.engine.step_trace_count,
                  gen.engine.draft.trace_count)
    try:
        def client(bat, out, i):
            p, mt = cases[i]
            time.sleep(0.002 * i)
            out[i] = bat.submit(p, max_tokens=mt).result(120)["tokens"]

        for bat, out in ((gen, results), (twin, ref)):
            ts = [threading.Thread(target=client, args=(bat, out, i))
                  for i in range(len(cases))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(180)
        requests_ok = sum(r is not None for r in results)
        bit_identical = results == ref and None not in results
    except Exception as e:      # noqa: BLE001 — a probe failure must
        # become a failed flag in the ONE JSON line, not a traceback
        errs.append(f"{type(e).__name__}: {e}")
        requests_ok, bit_identical = 0, False
    no_retrace = ((gen.engine.step_trace_count,
                   gen.engine.draft.trace_count) == trace_spec == (1, 1))
    snap = gen.metrics.snapshot()
    metrics_text = gen.metrics.render_prometheus()
    name = gen.metrics.name
    metrics_sane = (
        f"{name}_drafted_tokens_total "
        f"{snap['drafted_tokens_total']}" in metrics_text
        and f"{name}_accepted_tokens_total "
            f"{snap['accepted_tokens_total']}" in metrics_text
        and f"{name}_speculate_k {spec_args.speculate_k}" in metrics_text
        and "_spec_acceptance_rate " in metrics_text)
    out = {
        "metric": "speculative serving smoke (spec engine vs non-spec "
                  "twin)",
        "value": requests_ok, "unit": f"requests_ok/{len(cases)}",
        "vs_baseline": None,
        "speculate_k": spec_args.speculate_k,
        "draft_layers": spec_args.draft_layers,
        "bit_identical": bool(bit_identical),
        "drafted_tokens_total": snap["drafted_tokens_total"],
        "accepted_tokens_total": snap["accepted_tokens_total"],
        "spec_acceptance_rate": snap["spec_acceptance_rate"],
        "spec_tokens_per_step": snap["spec_tokens_per_step"],
        "no_retrace": bool(no_retrace),
        "metrics_sane": bool(metrics_sane),
    }
    if errs:
        out["errors"] = errs[:5]
    gen.close()
    twin.close()
    print(json.dumps(out), flush=True)
    passed = (requests_ok == len(cases) and bit_identical and no_retrace
              and metrics_sane and snap["drafted_tokens_total"] > 0
              and snap["spec_tokens_per_step"] >= 1.0)
    return 0 if passed else 2


def _smoke_sharded(args):
    """Tensor-parallel sharded-decode self-test (healthy_window.sh
    phase 19; docs/serving.md "Sharded decode"): the demo LM's one
    chunked step under an n=2 model-axis mesh serving concurrent
    staggered clients, every stream compared byte-for-byte against the
    single-chip twin — sharding may only ever change WHERE bytes live,
    never a token.  Speculation rides along (the draft trunk shards
    with its target), so the probe composes chunked admission + spec
    churn over the mesh at exactly one warm-up trace per jitted
    function.  Mesh evidence must land on the /metrics surface (the
    mesh_shards gauge).  XLA's host device count is fixed at backend
    init, so on a single-device machine the probe RE-EXECS itself with
    the forcing flag and forwards the child's JSON line + exit code.
    Prints ONE JSON line; returns the process exit code."""
    import copy
    import os
    import subprocess
    import threading
    import jax

    shards = max(2, int(getattr(args, "mesh_shards", 0) or 2))
    if len(jax.devices()) < shards:
        env = dict(os.environ)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={shards}").strip()
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.serving",
             "--smoke-sharded", "--mesh-shards", str(shards),
             "--kv-layout", args.kv_layout],
            env=env, capture_output=True, text=True, timeout=900)
        sys.stderr.write(proc.stderr[-2000:])
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        print(lines[-1] if lines else json.dumps(
            {"metric": "sharded serving smoke", "value": 0,
             "errors": [f"child produced no output, rc="
                        f"{proc.returncode}"]}), flush=True)
        return proc.returncode

    sh_args = copy.copy(args)
    sh_args.mesh_shards = shards
    sh_args.prefill_chunk = min(4, args.prefill_chunk or 4) or 4
    sh_args.speculate_k = max(1, getattr(args, "speculate_k", 0) or 2)
    sh_args.draft_layers = max(1, getattr(args, "draft_layers", 1) or 1)
    gen = _demo_gen_batcher(sh_args, tiny=True)
    twin_args = copy.copy(sh_args)
    twin_args.mesh_shards = 0
    twin = _demo_gen_batcher(twin_args, tiny=True)
    rng = np.random.RandomState(0)
    cases = [(rng.randint(1, 256, int(n)).astype(np.int64), int(m))
             for n, m in ((4, 12), (9, 8), (3, 14), (12, 10))]
    errs, results, ref = [], [None] * len(cases), [None] * len(cases)
    traces = (gen.engine.step_trace_count, gen.engine.draft.trace_count)
    try:
        def client(bat, out, i):
            p, mt = cases[i]
            time.sleep(0.002 * i)
            out[i] = bat.submit(p, max_tokens=mt).result(120)["tokens"]

        for bat, out in ((gen, results), (twin, ref)):
            ts = [threading.Thread(target=client, args=(bat, out, i))
                  for i in range(len(cases))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(180)
        requests_ok = sum(r is not None for r in results)
        bit_identical = results == ref and None not in results
    except Exception as e:      # noqa: BLE001 — a probe failure must
        # become a failed flag in the ONE JSON line, not a traceback
        errs.append(f"{type(e).__name__}: {e}")
        requests_ok, bit_identical = 0, False
    no_retrace = ((gen.engine.step_trace_count,
                   gen.engine.draft.trace_count) == traces == (1, 1))
    snap = gen.metrics.snapshot()
    metrics_text = gen.metrics.render_prometheus()
    name = gen.metrics.name
    metrics_sane = (snap["mesh_shards"] == shards
                    and f"{name}_mesh_shards {shards}" in metrics_text
                    and twin.metrics.snapshot()["mesh_shards"] == 1)
    out = {
        "metric": "sharded serving smoke (n-chip mesh vs single-chip "
                  "twin)",
        "value": requests_ok, "unit": f"requests_ok/{len(cases)}",
        "vs_baseline": None,
        "mesh_shards": snap["mesh_shards"],
        "devices": len(jax.devices()),
        "kv_layout": args.kv_layout,
        "speculate_k": sh_args.speculate_k,
        "bit_identical": bool(bit_identical),
        "no_retrace": bool(no_retrace),
        "metrics_sane": bool(metrics_sane),
    }
    if errs:
        out["errors"] = errs[:5]
    gen.close()
    twin.close()
    print(json.dumps(out), flush=True)
    passed = (requests_ok == len(cases) and bit_identical and no_retrace
              and metrics_sane)
    return 0 if passed else 2


def _write_port_file(path, port):
    """Publish the BOUND port (meaningful with --port 0) atomically —
    the fleet supervisor (serving/fleet.py) spawns replicas on ephemeral
    ports and discovers them here; a partial read must be impossible."""
    import os
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{port}\n")
    os.replace(tmp, path)


def main(argv=None):
    from paddle_tpu.utils.flags import FLAGS
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving",
        description="dynamic-batching inference server")
    ap.add_argument("--artifact", help="exported StableHLO artifact")
    ap.add_argument("--artifacts",
                    help="glob of bucketed artifacts (model.b*.shlo)")
    ap.add_argument("--demo", action="store_true",
                    help="serve the built-in tiny MLP")
    ap.add_argument("--demo-generate", action="store_true",
                    help="serve the built-in tiny LM behind the "
                         "continuous-batching /v1/generate")
    ap.add_argument("--buckets", default=FLAGS.serving_buckets,
                    help="batch bucket ladder for --demo (artifacts carry "
                         "their own)")
    ap.add_argument("--gen-slots", type=int, default=FLAGS.serving_gen_slots)
    ap.add_argument("--gen-max-len", type=int,
                    default=FLAGS.serving_gen_max_len)
    ap.add_argument("--gen-prefill-buckets",
                    default=FLAGS.serving_gen_prefill_buckets)
    ap.add_argument("--gen-max-tokens", type=int,
                    default=FLAGS.serving_gen_max_tokens)
    # ---- paged KV cache (serving/kv_pool.py; docs/serving.md §5) ----
    ap.add_argument("--kv-layout", default=FLAGS.serving_kv_layout,
                    choices=("slab", "paged"),
                    help="decode KV-cache layout: slab reserves max_len "
                         "per slot; paged packs a shared block pool with "
                         "prefix sharing")
    ap.add_argument("--kv-block-size", type=int,
                    default=FLAGS.serving_kv_block_size)
    ap.add_argument("--kv-num-blocks", type=int,
                    default=FLAGS.serving_kv_num_blocks,
                    help="paged pool size incl. the scratch block "
                         "(0 = the slab-equivalent byte budget)")
    ap.add_argument("--kv-prefix-cache",
                    type=lambda v: v.lower() in ("1", "true", "yes"),
                    default=FLAGS.serving_kv_prefix_cache)
    ap.add_argument("--kv-host-bytes", type=int,
                    default=FLAGS.serving_kv_host_bytes,
                    help="host-RAM spill-tier byte cap (hierarchical "
                         "KV: evicted prefix chains spill to host and "
                         "restore asynchronously on the next hit when "
                         "the analytic model predicts restore beats "
                         "recompute; 0 = tier off; paged + "
                         "prefix-cache only)")
    # ---- disaggregated serving (serving/transfer.py; docs/serving.md
    # "Disaggregated serving") ----
    ap.add_argument("--role", default=FLAGS.serving_role,
                    choices=("prefill", "decode", "mixed"),
                    help="disaggregated-serving role, advertised on "
                         "/metrics as serving_role{role=...}: the "
                         "router sends new prompts to the prefill pool "
                         "and at the first token hands the stream to a "
                         "decode replica by shipping chain key + "
                         "continuation (KV blocks ride /v1/kv/export); "
                         "mixed (the default) serves both phases")
    # ---- quantized serving (quant/; docs/serving.md) ----
    ap.add_argument("--kv-dtype", default=FLAGS.serving_kv_dtype,
                    choices=("float32", "int8"),
                    help="KV-cache storage dtype: int8 stores quantized "
                         "K/V + per-head scale sidecars (halved+ KV "
                         "bytes; paged auto-sizing doubles the block "
                         "count at the same byte budget)")
    ap.add_argument("--quant-weights",
                    type=lambda v: v.lower() in ("1", "true", "yes"),
                    default=FLAGS.quant_weights,
                    help="serve per-channel int8 trunk weights "
                         "(quant/weights.py): int8 data + f32 scales "
                         "resident, dequant fused into each matmul")
    ap.add_argument("--pallas-decode", default=FLAGS.pallas_decode,
                    help="fused decode-attention kernels for the decode "
                         "step: auto (TPU only) | always (interpret "
                         "off-TPU) | off — docs/perf.md 'Fused decode "
                         "kernels'")
    # ---- unified chunked prefill (docs/serving.md "Chunked prefill") --
    ap.add_argument("--prefill-chunk", type=int,
                    default=FLAGS.serving_prefill_chunk,
                    help="fold prompt ingestion into the one decode "
                         "step as up-to-K-token chunks per slot per "
                         "step (the default serving mode); 0 = the "
                         "legacy per-bucket prefill ladder")
    ap.add_argument("--prefill-chunk-budget", type=int,
                    default=FLAGS.serving_prefill_chunk_budget,
                    help="max teacher-forced chunk lanes per step "
                         "across all slots (bounds TPOT jitter; "
                         "0 = unbounded)")
    # ---- speculative decoding (docs/serving.md "Speculative decoding")
    ap.add_argument("--speculate-k", type=int,
                    default=FLAGS.serving_speculate_k,
                    help="draft tokens proposed per feeding slot per "
                         "step; the one chunked step scores every "
                         "drafted lane and each step nets 1 + accepted "
                         "tokens (0 = off; requires --prefill-chunk)")
    ap.add_argument("--draft-layers", type=int,
                    default=FLAGS.serving_draft_layers,
                    help="trunk depth of the draft derived from the "
                         "target (first N enc blocks; embedding/vocab "
                         "shared)")
    # ---- tensor-parallel sharded decode (docs/serving.md "Sharded
    # decode") ----
    ap.add_argument("--mesh-shards", type=int,
                    default=FLAGS.serving_mesh_shards,
                    help="run the one chunked step under an N-chip "
                         "model-axis mesh (heads/KV/vocab striped, "
                         "streams bit-identical to single-chip; "
                         "requires --prefill-chunk > 0); 0/1 = "
                         "single-chip")
    ap.add_argument("--pallas-prefill", default=FLAGS.pallas_prefill,
                    help="route the legacy ladder's lm_prefill causal "
                         "pass through the flash kernel (no [Tp, Tp] "
                         "scores): auto (TPU only) | always | off")
    ap.add_argument("--pallas-prefill-quant",
                    default=FLAGS.pallas_prefill_quant,
                    help="int8-cache prefill through the int8 flash "
                         "kernel (streams the quantized bytes + scale "
                         "sidecars, no f32 cache widen): auto (TPU "
                         "only) | always | off — docs/perf.md 'Int8 "
                         "flash prefill'")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=FLAGS.serving_port)
    ap.add_argument("--port-file",
                    help="write the BOUND port here once listening "
                         "(atomic; pairs with --port 0 for fleet-managed "
                         "replicas, serving/fleet.py)")
    ap.add_argument("--max-batch-size", type=int,
                    default=FLAGS.serving_max_batch_size or None)
    ap.add_argument("--max-delay-ms", type=float,
                    default=FLAGS.serving_max_delay_ms)
    ap.add_argument("--queue-size", type=int,
                    default=FLAGS.serving_queue_size)
    ap.add_argument("--deadline-ms", type=float,
                    default=FLAGS.serving_deadline_ms or None)
    ap.add_argument("--smoke", action="store_true",
                    help="self-test on an ephemeral port, print one JSON "
                         "line, exit")
    ap.add_argument("--smoke-generate", action="store_true",
                    help="generation self-test on an ephemeral port, "
                         "print one JSON line, exit")
    ap.add_argument("--smoke-paged", action="store_true",
                    help="paged-KV self-test: shared-system-prompt "
                         "clients over kv_layout=paged, prefix hits + "
                         "CoW fork recorded, streams bit-identical to "
                         "the slab layout; one JSON line, exit")
    ap.add_argument("--smoke-spill", action="store_true",
                    help="hierarchical-KV self-test: tiny paged pool + "
                         "host spill tier, churn forces eviction, the "
                         "returning shared prefix restore-hits with "
                         "zero prefill chunk lanes, bit-identical to a "
                         "tier-less twin, spill/restore evidence in "
                         "/metrics; one JSON line, exit")
    ap.add_argument("--smoke-decode-fused", action="store_true",
                    help="fused decode-kernel self-test: the demo "
                         "generation drive with pallas_decode=always "
                         "(slab + paged), streams bit-identical to a "
                         "reference-path twin, 0 retraces; one JSON "
                         "line, exit")
    ap.add_argument("--smoke-chunked", action="store_true",
                    help="chunked-prefill self-test: a long prompt "
                         "admitted MID-DECODE must chunk through the "
                         "unified step while in-flight streams keep "
                         "emitting, every stream bit-identical to the "
                         "legacy-ladder twin; one JSON line, exit")
    ap.add_argument("--smoke-quant", action="store_true",
                    help="quantized-serving self-test: int8-KV paged "
                         "engine vs a fp32 twin within the committed "
                         "quality budget, int8-KV+weights engine exact "
                         "vs the quantized oracle, kv_blocks_total "
                         "doubled at equal bytes; one JSON line, exit")
    ap.add_argument("--smoke-quant-prefill", action="store_true",
                    help="end-to-end low-precision self-test: int8 "
                         "flash prefill within the committed logit "
                         "budget vs the fp32 twin with a bit-exact "
                         "int8 cache vs sequential steps, plus 3-step "
                         "int8-trainer loss parity; one JSON line, "
                         "exit")
    ap.add_argument("--smoke-speculative", action="store_true",
                    help="speculative-decoding self-test: spec engine "
                         "vs a non-spec twin under concurrent clients, "
                         "streams bit-identical, acceptance-rate "
                         "evidence in /metrics; one JSON line, exit")
    ap.add_argument("--smoke-sharded", action="store_true",
                    help="sharded-decode self-test: n=2 forced host "
                         "mesh (re-execs itself with XLA_FLAGS when "
                         "single-device), concurrent streams "
                         "bit-identical to the single-chip twin, "
                         "mesh_shards evidence in /metrics; one JSON "
                         "line, exit")
    # ---- resilience (docs/serving.md §6) ----
    ap.add_argument("--drain-timeout-s", type=float,
                    default=FLAGS.serving_drain_timeout_s,
                    help="hard deadline for the SIGTERM graceful drain")
    ap.add_argument("--step-deadline-ms", type=float,
                    default=FLAGS.resilience_step_deadline_ms or None,
                    help="decode-step watchdog deadline (0/unset = off)")
    ap.add_argument("--breaker-threshold", type=int,
                    default=FLAGS.resilience_breaker_threshold)
    ap.add_argument("--breaker-cooldown-s", type=float,
                    default=FLAGS.resilience_breaker_cooldown_s)
    ap.add_argument("--fault-spec", default=FLAGS.resilience_fault_spec,
                    help="deterministic fault-injection spec "
                         "(resilience/faults.py; chaos testing only)")
    # ---- request tracing (obs/trace.py; docs/observability.md) ----
    ap.add_argument("--obs-trace",
                    type=lambda v: v.lower() in ("1", "true", "yes"),
                    default=FLAGS.obs_trace_enable,
                    help="per-request span tracing: /debug/traces + "
                         "trace_id propagation/echo")
    ap.add_argument("--obs-trace-sample", type=float,
                    default=FLAGS.obs_trace_sample)
    ap.add_argument("--obs-trace-ring", type=int,
                    default=FLAGS.obs_trace_ring)
    args = ap.parse_args(argv)
    # kernel selection is read at TRACE time — push the flags before any
    # engine is constructed
    FLAGS.pallas_decode = args.pallas_decode
    FLAGS.pallas_prefill = args.pallas_prefill
    FLAGS.pallas_prefill_quant = args.pallas_prefill_quant
    if args.fault_spec:
        from paddle_tpu.resilience import faults
        faults.install_spec(args.fault_spec)
        logger.warning("fault injection ACTIVE: %s", args.fault_spec)
    if args.obs_trace:
        obstrace.enable(sample=args.obs_trace_sample,
                        capacity=args.obs_trace_ring)
    if args.smoke and not (args.artifact or args.artifacts):
        args.demo = True
    if args.smoke:
        # a generous batch window so the smoke's concurrent clients
        # reliably coalesce (the occupancy>1 assertion) even on a loaded
        # CI machine
        args.max_delay_ms = max(args.max_delay_ms, 50.0)

    if args.smoke_generate:
        return _smoke_generate(_demo_gen_batcher(args, tiny=True))
    if args.smoke_paged:
        return _smoke_paged(args)
    if args.smoke_spill:
        return _smoke_spill(args)
    if args.smoke_decode_fused:
        return _smoke_decode_fused(args)
    if args.smoke_chunked:
        return _smoke_chunked(args)
    if args.smoke_quant:
        return _smoke_quant(args)
    if args.smoke_quant_prefill:
        return _smoke_quant_prefill(args)
    if args.smoke_speculative:
        return _smoke_speculative(args)
    if args.smoke_sharded:
        return _smoke_sharded(args)
    if args.demo_generate and not (args.artifact or args.artifacts
                                   or args.demo):
        # generation-only server: no /v1/infer batcher
        gen_batcher = _demo_gen_batcher(args)
        gen_batcher.metrics.set_serving_role(args.role)
        httpd = make_server(None, args.host, args.port,
                            gen_batcher=gen_batcher)
        # the bound port is the replica's identity in a merged fleet
        # Chrome trace (processes = router/replicas)
        obstrace.set_process(f"replica:{httpd.port}")
        if args.port_file:
            _write_port_file(args.port_file, httpd.port)
        logger.info("serving %s on http://%s:%d (/v1/generate: %d slots, "
                    "max_len %d)", gen_batcher.engine.name, args.host,
                    httpd.port, gen_batcher.engine.num_slots,
                    gen_batcher.engine.max_len)
        return _serve(httpd, None, gen_batcher,
                      drain_timeout_s=args.drain_timeout_s)

    engine = _build_engine(args)
    batcher = Batcher(engine, max_batch_size=args.max_batch_size,
                      max_delay_ms=args.max_delay_ms,
                      queue_size=args.queue_size,
                      default_deadline_ms=args.deadline_ms)
    if args.smoke:
        return _smoke(batcher)

    # combined server: the generation plane shares the inference
    # batcher's metrics, so the ONE /metrics page reports both
    gen_batcher = (_demo_gen_batcher(args, metrics=engine.metrics)
                   if args.demo_generate else None)
    engine.metrics.set_serving_role(args.role)
    httpd = make_server(batcher, args.host, args.port,
                        gen_batcher=gen_batcher)
    obstrace.set_process(f"replica:{httpd.port}")
    if args.port_file:
        _write_port_file(args.port_file, httpd.port)
    logger.info("serving %s on http://%s:%d (buckets %s, max_delay %.1fms, "
                "queue %d)", engine.name, args.host, httpd.port,
                list(engine.buckets), args.max_delay_ms, args.queue_size)
    return _serve(httpd, batcher, gen_batcher,
                  drain_timeout_s=args.drain_timeout_s)


def _make_drain_handler(httpd, state, drain_timeout_s, force_exit):
    """The SIGTERM/SIGINT handler with a HARD deadline (docs/serving.md
    §5): the first signal starts a graceful drain AND arms a watchdog —
    if the drain has not completed within ``drain_timeout_s`` (a wedged
    in-flight batch, a handler stuck on a dead socket), the process
    force-exits instead of hanging shutdown forever.  A SECOND signal
    force-exits immediately.  Factored out (and ``force_exit``
    injectable) so both paths are unit-testable without killing the
    test runner."""

    def _drain(signum, frame):
        state["signals"] = state.get("signals", 0) + 1
        if state["signals"] > 1:
            logger.warning("second SIGTERM: forcing immediate exit")
            force_exit(130)
            return
        logger.info("SIGTERM: draining (no new admissions, finishing "
                    "queued requests; hard deadline %.0fs, second "
                    "SIGTERM forces exit)", drain_timeout_s or 0.0)
        threading.Thread(target=httpd.shutdown, daemon=True).start()
        if drain_timeout_s and drain_timeout_s > 0:
            def watchdog():
                time.sleep(drain_timeout_s)
                if not state.get("drained"):
                    logger.warning("drain did not complete within %.0fs; "
                                   "forcing exit", drain_timeout_s)
                    force_exit(3)
            threading.Thread(target=watchdog, daemon=True,
                             name="drain-deadline").start()
    return _drain


def _serve(httpd, batcher, gen_batcher, drain_timeout_s=None):
    import os
    if drain_timeout_s is None:
        from paddle_tpu.utils.flags import FLAGS
        drain_timeout_s = FLAGS.serving_drain_timeout_s
    httpd.drain_timeout_s = drain_timeout_s
    state = {}
    _drain = _make_drain_handler(httpd, state, drain_timeout_s, os._exit)
    try:
        signal.signal(signal.SIGTERM, _drain)
        signal.signal(signal.SIGINT, _drain)
    except ValueError:
        pass        # not the main thread (embedded use)
    try:
        httpd.serve_forever()
    finally:
        # order matters: the drain resolves every in-flight future, THEN
        # server_close() joins the handler threads (block_on_close) so
        # their responses reach the sockets before the interpreter exits
        # — otherwise the work the drain completed is dropped on the wire
        if batcher is not None:
            batcher.close(drain=True)
        if gen_batcher is not None:
            gen_batcher.close(drain=True)
        state["drained"] = True     # disarms the drain-deadline watchdog
        httpd.server_close()
        metrics = (batcher or gen_batcher).metrics
        logger.info("serving stopped; %d responses served",
                    metrics.responses_total)
    return 0


if __name__ == "__main__":
    sys.exit(main())
