"""Serving runtime: dynamic-batching inference over bucketed AOT
executables (docs/serving.md §3).

    engine.py   InferenceEngine — one XLA executable per batch bucket
                (in-process forward or exported StableHLO ladder), pad to
                bucket / slice back, warm-up, analytic lower() hook
    batcher.py  Batcher — bounded queue + background batching thread,
                futures, admission control, deadlines, graceful drain
    server.py   JSON/HTTP front-end (/v1/infer, /healthz, /metrics) + CLI
    metrics.py  ServingMetrics — latency percentiles, occupancy, padding
                waste, queue depth; Prometheus text at /metrics

    python -m paddle_tpu.serving --artifacts 'model.b*.shlo' --port 8080
"""

from paddle_tpu.serving.batcher import (BatchExecutionError, Batcher,
                                        DeadlineExceededError,
                                        OverloadedError, ShutdownError)
from paddle_tpu.serving.engine import (DEFAULT_BUCKETS, InferenceEngine,
                                       InvalidRequestError)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.server import make_server

__all__ = [
    "Batcher", "BatchExecutionError", "DeadlineExceededError",
    "DEFAULT_BUCKETS", "InferenceEngine", "InvalidRequestError",
    "OverloadedError", "ServingMetrics", "ShutdownError", "make_server",
]
