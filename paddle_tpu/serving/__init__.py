"""Serving runtime: dynamic-batching inference over bucketed AOT
executables (docs/serving.md §3) and continuous-batching generation
(docs/serving.md §4).

    engine.py        InferenceEngine — one XLA executable per batch bucket
                     (in-process forward or exported StableHLO ladder),
                     pad to bucket / slice back, warm-up, analytic
                     lower() hook
    batcher.py       Batcher — bounded queue + background batching thread,
                     futures, admission control, deadlines, graceful drain
    decode_engine.py DecodeEngine + GenerationBatcher — slot-based
                     continuous-batching LM decode over a fixed KV-cache
                     slab (prefill through the bucketed engine ladder,
                     per-token streaming, TTFT/TPOT metrics)
    server.py        JSON/HTTP front-end (/v1/infer, /v1/generate,
                     /healthz liveness, /readyz readiness, /metrics)
                     + CLI; 429/503 carry Retry-After, SIGTERM drain
                     under a hard deadline (docs/serving.md §6)
    metrics.py       ServingMetrics — latency/TTFT/TPOT percentiles,
                     occupancy, padding waste, slot evictions, queue
                     depth; Prometheus text at /metrics
    fleet.py         ReplicaSupervisor — spawn/health/restart N replica
                     subprocesses (exp backoff + seeded jitter, restart-
                     storm breaker, rolling drain; docs/serving.md §7)
    router.py        Router — readiness-gated least-loaded dispatch,
                     outlier ejection, bounded retry, hedging, and
                     cross-replica MID-STREAM failover (bit-identical
                     greedy streams; docs/serving.md §7)
    overload.py      OverloadController — AIMD concurrency limit ahead
                     of dispatch, priority-class + deadline-aware
                     shedding (honest 429 + Retry-After), brownout
                     ladder under sustained SLO breach
                     (docs/serving.md §8)
    autoscaler.py    Autoscaler — trace-driven control loop sizing the
                     replica fleet to its TTFT SLO: target tracking
                     with hysteresis + cooldowns, spawn-to-readiness
                     scale-out, zero-failure drain scale-in, journaled
                     replayable decisions (docs/serving.md §8)

    python -m paddle_tpu.serving --artifacts 'model.b*.shlo' --port 8080
    python -m paddle_tpu.serving --demo-generate --port 8080
    python -m paddle_tpu.serving.router --replicas 2 --port 8000
    python -m paddle_tpu.serving.autoscaler --min-replicas 1 --max-replicas 4
"""

from paddle_tpu.resilience.supervisor import BreakerOpenError, Supervisor
from paddle_tpu.serving.autoscaler import Autoscaler
from paddle_tpu.serving.batcher import (BatchExecutionError, Batcher,
                                        DeadlineExceededError,
                                        OverloadedError, ShutdownError)
from paddle_tpu.serving.decode_engine import DecodeEngine, GenerationBatcher
from paddle_tpu.serving.engine import (DEFAULT_BUCKETS, InferenceEngine,
                                       InvalidRequestError)
from paddle_tpu.serving.fleet import ReplicaSupervisor
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.overload import (AIMDLimiter, BrownoutLadder,
                                         OverloadController, ShedError)
from paddle_tpu.serving.router import Router, RouterMetrics
from paddle_tpu.serving.server import make_server

__all__ = [
    "AIMDLimiter", "Autoscaler", "Batcher", "BatchExecutionError",
    "BreakerOpenError", "BrownoutLadder", "DeadlineExceededError",
    "DecodeEngine", "DEFAULT_BUCKETS", "GenerationBatcher",
    "InferenceEngine", "InvalidRequestError", "OverloadedError",
    "OverloadController", "ReplicaSupervisor", "Router", "RouterMetrics",
    "ServingMetrics", "ShedError", "ShutdownError", "Supervisor",
    "make_server",
]
