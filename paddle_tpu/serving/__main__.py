"""``python -m paddle_tpu.serving`` — the serving CLI (server.py)."""

import sys

from paddle_tpu.serving.server import main

sys.exit(main())
