"""Paged KV cache: block-pool allocator + copy-on-write prefix sharing.

The slab layout (``DecodeEngine(kv_layout="slab")``) reserves
``max_len`` KV positions per slot no matter how long the request
actually runs — the reservation waste PagedAttention (vLLM, SOSP'23)
eliminates — and at fleet scale most traffic shares a handful of
system-prompt prefixes the slab recomputes and stores once PER SLOT.
This module is the host half of the paged answer (docs/serving.md §5):

* ``BlockPool`` — a fixed pool of ``num_blocks`` KV blocks of
  ``block_size`` positions each (the device arrays live in the engine:
  per-layer ``[num_blocks, block_size, Dkv]``,
  ``transformer.init_lm_cache_paged``).  Free-list allocation with
  per-block REFCOUNTS: a physical block referenced by several slot
  chains (and/or the prefix index) stays resident until the last
  reference releases it.  Block 0 is reserved as the scratch block free
  slot rows point at; allocatable ids are ``1..num_blocks-1``.

* ``PrefixIndex`` — maps block-aligned prompt prefixes (token tuples of
  length ``k * block_size``) to the already-resident block chains that
  hold their K/V.  A new request whose prompt starts with a cached
  prefix admits by TAKING REFERENCES to those physical blocks instead
  of re-prefilling them: duplicate KV bytes and duplicate prefill
  compute both disappear.  LRU: under pool pressure the allocator
  evicts the stalest entries (their blocks free once no slot shares
  them).

* ``PagedKVState`` — per-engine bookkeeping tying the two together:
  the per-slot block tables (``[num_slots, blocks_per_row]`` int32 fed
  to the jitted step as DATA — churn never retraces), per-slot chain
  ledgers, and the write-exclusivity rule that yields COPY-ON-WRITE: a
  slot about to write into a block whose refcount exceeds 1 first forks
  it (the engine device-copies the block, the table entry swaps to the
  private copy) so shared prefix blocks are physically immutable while
  referenced.

Everything here is host-side numpy/bookkeeping between steps; the one
jitted step (``transformer.lm_decode_step_paged``) only ever sees
fixed-shape pools and tables.  ``check()`` verifies the refcount ledger
(no leak, no double-free) — the chaos tests run it after every fault
matrix pass.

The HIERARCHICAL tier (docs/serving.md §5 "Hierarchical KV") extends
the story below HBM: ``HostTier`` is an LRU byte-capped host-RAM store
of SPILLED prefix chains — when ``PrefixIndex.evict_lru`` drops an
entry under pool pressure, an ``on_evict`` hook (the engine's) gathers
the chain's device blocks and ``serialize_chain``s them into the tier,
keyed by the SAME block-aligned prefix key; a later prompt that would
have recomputed that prefix instead restores it asynchronously (the
tier's ``TransferWorker`` thread deserializes + stages device chunks
while decode steps keep running) into freshly claimed blocks and seats
by reference like any resident hit.  ``serialize_chain``/
``restore_chain`` are the relocatable wire format (version byte +
trunk signature) the ROADMAP item 2(b) cross-replica handoff reuses.
"""

import collections
import json
import threading

import numpy as np

from paddle_tpu.obs import trace as obstrace
from paddle_tpu.utils.error import ConfigError
from paddle_tpu.utils.logging import logger

SCRATCH_BLOCK = 0

# serialize_chain wire-format version: byte 0 of every blob.  Bump on
# any layout change — restore_chain rejects other versions, so a
# cross-replica peer (item 2(b)) can never mis-parse a newer blob.
WIRE_VERSION = 1

# Default decoded-blob ceiling at the NETWORK boundary (serving/
# transfer.py) — a garbled or malicious peer's length prefix / shape
# manifest must never drive an allocation.  The host tier itself is
# byte-capped separately; this bounds a SINGLE blob.
MAX_CHAIN_BLOB_BYTES = 1 << 30


class WireFormatError(ValueError):
    """A chain blob violates the ``serialize_chain`` wire format
    (truncated, oversized, inconsistent manifest, foreign trunk).
    Subclasses ``ValueError`` so every existing rejection path — and
    test — keeps working; the network receiver catches THIS to count a
    rejected peer blob without masking programming errors."""


class WireVersionError(WireFormatError):
    """The blob's version byte (or header version field) is not the
    ``WIRE_VERSION`` this build speaks — an EXPLICIT mismatch, never a
    silent misparse: a newer peer's layout change lands here instead of
    inside the manifest parser."""


def slab_equivalent_blocks(num_slots, max_len, block_size,
                           kv_dtype="float32", mesh_shards=1):
    """Auto pool size (``DecodeEngine(kv_num_blocks=0)``) at the SLAB-
    EQUIVALENT **per-chip** byte budget: an f32 pool gets exactly the
    slab's ``num_slots * ceil(max_len / block_size)`` blocks (same KV
    bytes, strictly more packable).  ``kv_dtype="int8"`` DOUBLES the
    block count inside that same budget: an int8 block plus its f32
    per-(position, head) scale sidecar costs ``(1/4 + 1/head_dim)`` of
    the f32 block's bytes (quant/kv.kv_bytes_per_position), i.e. at
    most half for head_dim >= 4 — so twice the blocks still fit, with
    headroom that grows with head_dim.  ``mesh_shards=n`` (the sharded
    decode mesh, docs/serving.md "Sharded decode") MULTIPLIES by n: a
    chip holds only its ``Hkv/n`` head stripe of each block, so the
    single-chip per-chip budget holds n× the block count — the capacity
    win tensor-parallel serving exists for.  +1 everywhere for the
    reserved scratch block 0."""
    per_row = -(-int(max_len) // int(block_size))
    blocks = int(num_slots) * per_row
    if kv_dtype == "int8":
        blocks *= 2
    blocks *= max(1, int(mesh_shards))
    return blocks + 1


class InsufficientBlocksError(RuntimeError):
    """The pool cannot supply the requested blocks even after evicting
    every prefix-index entry.  Admission defers the request (it is NOT a
    client error); mid-decode the engine preempts a victim slot instead
    (``evictions{reason="pool_exhausted"}``)."""


class RestorePendingError(InsufficientBlocksError):
    """A host-tier restore covering this request's prefix is in flight:
    blocks are claimed and the payload is crossing the link, so seating
    now would recompute K/V the transfer is about to deliver.  Subclasses
    ``InsufficientBlocksError`` on purpose — every defer-and-retry seam
    (``_waiting`` / ``_preempted``) already treats that as "space, not
    failure", and the retry after the restore commits seats as an
    ordinary resident prefix hit."""


def serialize_chain(tokens, covered, arrays, trunk_sig):
    """Pack one prefix chain's K/V payload into a RELOCATABLE blob: the
    block-aligned prefix key (``tokens``), the positions it covers, and
    each cache leaf's gathered block rows (int8 payload + f32 scale
    sidecars on a quantized engine — spilled bytes stay ~halved) as raw
    bytes behind a JSON manifest.  Nothing in the blob references block
    IDS — restore lands the payload in whatever blocks the destination
    pool hands out, which is exactly what lets the same format cross
    replicas (ROADMAP item 2(b)).

    Layout: 1 version byte, 8-byte little-endian header length, the
    JSON header ``{version, trunk_sig, tokens, covered, arrays:
    [{name, dtype, shape}...]}``, then each array's contiguous bytes in
    manifest order.  ``trunk_sig`` fingerprints the producing engine's
    trunk (dims + layers + kv dtype + block size); ``restore_chain``
    rejects a mismatch — K/V bytes are only relocatable between
    identical trunks."""
    arrays = list(arrays)
    header = {
        "version": WIRE_VERSION,
        "trunk_sig": str(trunk_sig),
        "tokens": [int(t) for t in tokens],
        "covered": int(covered),
        "arrays": [{"name": str(n), "dtype": str(a.dtype),
                    "shape": [int(s) for s in a.shape]}
                   for n, a in arrays],
    }
    hdr = json.dumps(header, sort_keys=True).encode("utf-8")
    parts = [bytes([WIRE_VERSION]), len(hdr).to_bytes(8, "little"), hdr]
    for _n, a in arrays:
        parts.append(np.ascontiguousarray(a).tobytes())
    return b"".join(parts)


def peek_chain_header(blob, trunk_sig=None, max_bytes=None):
    """Parse and validate ONLY the blob's envelope — version byte,
    header length, JSON header, optional trunk-signature and size
    bound — without touching (or allocating for) the array payload.
    The network receiver (serving/transfer.py) calls this on received
    bytes BEFORE anything is staged, so a garbled peer is rejected at
    the manifest, never mid-``frombuffer``.  Returns the header dict.

    Raises ``WireVersionError`` on a version mismatch and
    ``WireFormatError`` on everything else (both ``ValueError``)."""
    if max_bytes is not None and len(blob) > int(max_bytes):
        raise WireFormatError(
            f"chain blob of {len(blob)} byte(s) exceeds the "
            f"{int(max_bytes)}-byte receive bound")
    if len(blob) < 9:
        raise WireFormatError(
            f"chain blob truncated: {len(blob)} byte(s)")
    if blob[0] != WIRE_VERSION:
        raise WireVersionError(f"chain blob version {blob[0]} != "
                               f"{WIRE_VERSION} (wire format mismatch)")
    hlen = int.from_bytes(blob[1:9], "little")
    if 9 + hlen > len(blob):
        raise WireFormatError("chain blob header overruns the payload")
    try:
        header = json.loads(blob[9:9 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireFormatError(f"chain blob header is not valid JSON: "
                              f"{e}") from None
    if not isinstance(header, dict):
        raise WireFormatError("chain blob header is not a JSON object")
    if header.get("version") != WIRE_VERSION:
        raise WireVersionError(
            f"chain header version {header.get('version')} "
            f"!= {WIRE_VERSION}")
    if trunk_sig is not None and header.get("trunk_sig") != str(trunk_sig):
        raise WireFormatError(
            f"chain trunk signature {header.get('trunk_sig')!r} does not "
            f"match this engine's {str(trunk_sig)!r}: K/V bytes are only "
            "relocatable between identical trunks")
    return header


def restore_chain(blob, trunk_sig, max_bytes=None):
    """Inverse of ``serialize_chain``: returns ``(tokens_tuple,
    covered, [(name, ndarray), ...])``.  Raises ``WireVersionError`` on
    a version mismatch and ``WireFormatError`` (both ``ValueError``) on
    a trunk-signature mismatch or a truncated / oversized payload — a
    corrupt or foreign blob must never seat.  ``max_bytes`` bounds the
    whole blob BEFORE any manifest-driven decoding (the network-boundary
    defense; None = trusted local blob)."""
    header = peek_chain_header(blob, trunk_sig, max_bytes)
    hlen = int.from_bytes(blob[1:9], "little")
    off = 9 + hlen
    arrays = []
    for spec in header["arrays"]:
        try:
            dt = np.dtype(spec["dtype"])
            shape = tuple(int(s) for s in spec["shape"])
        except (TypeError, ValueError, KeyError) as e:
            raise WireFormatError(
                f"chain blob manifest is malformed: {e}") from None
        if any(s < 0 for s in shape):
            raise WireFormatError(
                f"chain blob array {spec.get('name')!r} declares a "
                "negative dimension")
        count = int(np.prod(shape, dtype=np.int64))
        nbytes = dt.itemsize * count
        if off + nbytes > len(blob):
            raise WireFormatError(f"chain blob truncated inside array "
                                  f"{spec['name']!r}")
        arrays.append((spec["name"],
                       np.frombuffer(blob, dt, count=count,
                                     offset=off).reshape(shape)))
        off += nbytes
    if off != len(blob):
        raise WireFormatError(f"chain blob holds {len(blob) - off} "
                              "trailing byte(s) past the manifest")
    return tuple(header["tokens"]), int(header["covered"]), arrays


class HostTier:
    """LRU host-RAM store of spilled prefix-chain blobs, byte-capped.

    The device-side ``PrefixIndex`` holds CHAINS (pool references); this
    tier holds their serialized PAYLOADS after eviction, keyed by the
    same block-aligned prefix keys, so the reusable-prefix working set
    is bounded by ``cap_bytes`` of host RAM instead of HBM.  LRU within
    the cap: ``put`` evicts the stalest blobs until the new one fits
    (spill-of-spill simply falls off the end — those prefixes recompute,
    exactly as they would with no tier).

    The tier also owns the bounded background transfer thread
    (``data/prefetch.TransferWorker``) restores run on: the engine
    submits a staging job (deserialize + per-block ``device_put``) and
    polls completions strictly BETWEEN decode steps, so the transfer
    overlaps compute and the donated cache is only ever written by the
    worker-thread seam.  All map state is lock-guarded — spills/probes
    happen on the batcher worker thread while ``/metrics`` reads the
    byte gauge from HTTP threads.
    """

    def __init__(self, cap_bytes=0, worker_depth=8):
        if int(cap_bytes) < 0:
            raise ConfigError(f"HostTier cap_bytes must be >= 0, got "
                              f"{cap_bytes}")
        self.cap_bytes = int(cap_bytes)
        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()  # key -> (covered, blob)
        self._bytes = 0
        self._worker_depth = int(worker_depth)
        self._worker = None         # lazy: tests exercise put/lookup
        #                             without ever paying for a thread

    def __len__(self):
        with self._lock:
            return len(self._entries)

    @property
    def bytes(self):
        """Current resident payload bytes (the host_tier_bytes gauge)."""
        with self._lock:
            return self._bytes

    # ------------------------------------------------------------ store

    def put(self, key, covered, blob):
        """Insert (or refresh) one spilled chain; evicts LRU entries
        until the tier fits ``cap_bytes`` again.  Returns the number of
        entries evicted to make room.  Strict-prefix entries of ``key``
        are dropped — the new blob's payload supersets theirs, and
        ``lookup`` probes longest-first anyway."""
        key = tuple(int(t) for t in key)
        dropped = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old[1])
            for k in [k for k in self._entries
                      if len(k) < len(key) and key[:len(k)] == k]:
                _cov, shadowed = self._entries.pop(k)
                self._bytes -= len(shadowed)
            self._entries[key] = (int(covered), blob)
            self._bytes += len(blob)
            while self.cap_bytes and self._bytes > self.cap_bytes \
                    and self._entries:
                _k, (_cov, dropped_blob) = self._entries.popitem(
                    last=False)
                self._bytes -= len(dropped_blob)
                dropped += 1
        return dropped

    def pop(self, key):
        """Remove and return ``(covered, blob)`` for ``key``, or None."""
        with self._lock:
            ent = self._entries.pop(tuple(int(t) for t in key), None)
            if ent is not None:
                self._bytes -= len(ent[1])
            return ent

    def covers(self, key):
        """True if some stored entry's key EXTENDS ``key`` (equal or
        longer, same leading tokens) — its payload supersets what a
        spill of ``key`` would store, so that spill is redundant."""
        key = tuple(int(t) for t in key)
        n = len(key)
        with self._lock:
            return any(len(k) >= n and k[:n] == key
                       for k in self._entries)

    def lookup(self, tokens, block_size):
        """Longest spilled coverage of ``tokens`` — the host-tier twin
        of ``PrefixIndex.lookup``: the exact probe first, then
        block-aligned prefixes descending.  Returns ``(key, covered,
        blob)`` or ``(None, 0, None)``.  The hit is an LRU touch; the
        entry stays resident until the restore COMMITS (an in-flight
        job going stale across a reset must not lose the payload)."""
        bs = int(block_size)
        toks = tuple(int(t) for t in tokens)
        with self._lock:
            ent = self._entries.get(toks)
            if ent is not None:
                self._entries.move_to_end(toks)
                return toks, ent[0], ent[1]
            for m in range(len(toks) // bs, 0, -1):
                ent = self._entries.get(toks[:m * bs])
                if ent is not None:
                    self._entries.move_to_end(toks[:m * bs])
                    return toks[:m * bs], ent[0], ent[1]
        return None, 0, None

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    # ------------------------------------------------------ transfer thread

    def submit(self, tag, fn):
        """Run ``fn`` on the tier's background transfer thread; the
        result arrives via ``poll()`` as ``(tag, result)``."""
        if self._worker is None:
            from paddle_tpu.data.prefetch import TransferWorker
            self._worker = TransferWorker(name="paddle-tpu-kv-restore",
                                          depth=self._worker_depth)
        self._worker.submit(tag, fn)

    def poll(self, timeout=0.0):
        """Next completed transfer job, or None.  The result may be a
        ``prefetch._Failure`` — the engine decides per-job fate (a
        failed restore falls back to recompute, never kills serving)."""
        if self._worker is None:
            return None
        return self._worker.poll(timeout=timeout)

    def close(self):
        if self._worker is not None:
            self._worker.close()
            self._worker = None


class BlockPool:
    """Free-list + refcount allocator over ``num_blocks`` KV blocks.

    ``alloc()`` hands out a block at refcount 1; ``share()`` adds a
    reference (a second slot chain or a prefix-index entry);
    ``release()`` drops one and returns the block to the free list at
    zero.  All host-side integers — the device arrays are the engine's.
    """

    def __init__(self, num_blocks, block_size):
        if num_blocks < 2:
            raise ConfigError("BlockPool needs num_blocks >= 2 (block 0 "
                              "is the reserved scratch block)")
        if block_size < 1:
            raise ConfigError("BlockPool needs block_size >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # pop() -> block 1 first; scratch block 0 is never allocatable
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = np.zeros((self.num_blocks,), np.int64)

    @property
    def num_allocatable(self):
        return self.num_blocks - 1

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_used(self):
        return self.num_allocatable - len(self._free)

    def refcount(self, bid):
        return int(self._ref[bid])

    def alloc(self):
        """One free block at refcount 1, or None when the pool is dry
        (callers then evict prefix-index entries / preempt a slot)."""
        if not self._free:
            return None
        bid = self._free.pop()
        self._ref[bid] = 1
        return bid

    def share(self, bid):
        if self._ref[bid] < 1:
            raise RuntimeError(f"BlockPool.share of unowned block {bid}")
        self._ref[bid] += 1
        return bid

    def release(self, bid):
        if self._ref[bid] < 1:
            raise RuntimeError(f"BlockPool.release of free block {bid} "
                               "(double free)")
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)

    def check(self):
        """Internal-consistency invariants: the free list and the
        refcounts partition the allocatable ids exactly.  Raises on any
        violation (leak or double-free would break one)."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("free list holds duplicates: "
                                 f"{sorted(self._free)}")
        if SCRATCH_BLOCK in free or self._ref[SCRATCH_BLOCK] != 0:
            raise AssertionError("scratch block 0 entered the allocator")
        held = {int(b) for b in np.nonzero(self._ref)[0]}
        if free & held:
            raise AssertionError(f"blocks both free and referenced: "
                                 f"{sorted(free & held)}")
        if len(free) + len(held) != self.num_allocatable:
            raise AssertionError(
                f"leaked blocks: {self.num_allocatable} allocatable != "
                f"{len(free)} free + {len(held)} held")


class PrefixIndex:
    """Prompt prefix -> resident block chain, LRU.

    Two key kinds share one map: every BLOCK-ALIGNED prefix of a
    registered prompt (token tuples of length ``k * block_size`` —
    reusable by any prompt sharing those leading blocks), plus the EXACT
    full prompt when its tail block is partial (reusable by EXACT
    duplicates only — ``lookup`` probes the exact key and block-aligned
    prefixes, so a LONGER probe sharing this prompt matches just the
    aligned portion — the seat then lands INSIDE the shared tail block
    and the first write copy-on-write forks it).  An entry holds ONE
    pool reference per block, so the
    chain outlives the slot that prefilled it.  ``lookup`` returns the
    LONGEST registered coverage of the probe (and refreshes its LRU
    position); ``evict_lru`` releases the stalest entry's references —
    the blocks actually free only once no slot chain shares them.
    """

    def __init__(self, pool, on_evict=None):
        self._pool = pool
        # spill hook (the engine's D2H gather + HostTier.put): called
        # with (key, covered, [bids]) BEFORE the references release —
        # the block contents must be read while still owned
        self._on_evict = on_evict
        self._entries = collections.OrderedDict()  # key -> (covered, [bids])

    def __len__(self):
        return len(self._entries)

    @property
    def block_refs(self):
        """Total (entry, block) references the index holds — the ledger
        term ``PagedKVState.check`` audits."""
        return sum(len(c) for _cov, c in self._entries.values())

    def _add(self, key, covered, blocks):
        if key in self._entries:
            # existing entries win — their blocks hold identical K/V by
            # determinism, and keeping them preserves their sharers
            self._entries.move_to_end(key)
            return
        self._entries[key] = (covered, [self._pool.share(b)
                                        for b in blocks])

    def register(self, tokens, chain):
        """Publish ``tokens`` (the real prefix of a just-admitted
        prompt, whose K/V ``chain`` holds): every block-aligned prefix,
        plus the exact full key when the tail block is partial."""
        bs = self._pool.block_size
        toks = tuple(int(t) for t in tokens)
        for m in range(1, len(toks) // bs + 1):
            self._add(toks[:m * bs], m * bs, chain[:m])
        if len(toks) % bs:
            self._add(toks, len(toks),
                      chain[:-(-len(toks) // bs)])

    def lookup(self, tokens):
        """Longest registered coverage of ``tokens``: the exact probe
        first (duplicate prompt — covers its partial tail too), then
        block-aligned prefixes descending.  Returns
        ``(covered_positions, [bids])`` or ``(0, [])``.  The hit is an
        LRU touch; references are NOT taken here — seating does."""
        bs = self._pool.block_size
        toks = tuple(int(t) for t in tokens)
        ent = self._entries.get(toks)
        if ent is not None:
            self._entries.move_to_end(toks)
            return ent[0], list(ent[1])
        for m in range(len(toks) // bs, 0, -1):
            ent = self._entries.get(toks[:m * bs])
            if ent is not None:
                self._entries.move_to_end(toks[:m * bs])
                return ent[0], list(ent[1])
        return 0, []

    def evict_lru(self):
        """Release the stalest entry's block references; True if one was
        evicted.  With a spill hook installed, the entry's key/coverage/
        chain are handed to it FIRST (the hook gathers the device bytes
        into the host tier) — a hook failure only loses the spill, never
        the eviction, so pool pressure always makes progress."""
        if not self._entries:
            return False
        key, (cov, chain) = self._entries.popitem(last=False)
        if self._on_evict is not None:
            try:
                self._on_evict(key, cov, list(chain))
            except Exception as e:  # noqa: BLE001 — a spill failure
                # must never wedge the allocator under pressure
                logger.warning("prefix spill of %d block(s) failed: "
                               "%s: %s", len(chain), type(e).__name__, e)
        for bid in chain:
            self._pool.release(bid)
        return True

    def clear(self):
        while self.evict_lru():
            pass


class PagedKVState:
    """Host bookkeeping for one paged ``DecodeEngine``: pool + prefix
    index + per-slot block tables/chains + the write-exclusivity plan.

    The engine owns every device operation (the jitted step, block
    write, block copy); this object only decides WHICH blocks — methods
    that need a device copy return the plan and the engine executes it.
    """

    def __init__(self, num_slots, num_blocks, block_size, max_len,
                 prefix_cache=True, on_evict=None):
        self.pool = BlockPool(num_blocks, block_size)
        self.index = PrefixIndex(self.pool, on_evict=on_evict) \
            if prefix_cache else None
        self.block_size = self.pool.block_size
        self.blocks_per_row = -(-int(max_len) // self.block_size)
        self.tables = np.zeros((int(num_slots), self.blocks_per_row),
                               np.int32)
        self._chains = [[] for _ in range(int(num_slots))]
        # host-tier restores in flight: prefix key -> [bids] claimed
        # ahead of the async transfer (refs held here so the pool can
        # never hand them out twice; committed into the index — or
        # released — when the restore lands or dies)
        self._pending = {}
        # admission order, for pool-pressure victim choice (youngest
        # first: cheapest replay, most blocks still ahead of it)
        self._seat_seq = np.zeros((int(num_slots),), np.int64)
        self._seq = 0

    # ------------------------------------------------------------ sizing

    def blocks_for(self, n_positions):
        return -(-int(n_positions) // self.block_size)

    def can_admit(self, n_positions):
        """Could ``blocks_for(n_positions)`` blocks be produced right
        now (free list + whatever evicting the whole prefix index would
        release)?  Conservative: index blocks shared by live slots are
        counted as unevictable."""
        need = self.blocks_for(n_positions)
        free = self.pool.num_free
        if free >= need:
            return True
        if self.index is None:
            return False
        live = {b for c in self._chains for b in c}
        evictable = {b for _cov, chain in self.index._entries.values()
                     for b in chain
                     if b not in live and self.pool.refcount(b) >= 1}
        return free + len(evictable) >= need

    def _alloc(self):
        """One block, evicting LRU prefix entries under pressure;
        None when truly dry (the caller preempts a slot)."""
        bid = self.pool.alloc()
        while bid is None and self.index is not None \
                and self.index.evict_lru():
            bid = self.pool.alloc()
        return bid

    # ------------------------------------------------------------ seating

    def seat_fresh(self, slot, n_positions):
        """Claim private blocks covering ``[0, n_positions)`` for a
        just-prefilled admission; returns the chain (the engine writes
        the prefill rows into them).  All-or-nothing: on exhaustion
        nothing is claimed and ``InsufficientBlocksError`` raises (the
        batcher defers the request)."""
        need = self.blocks_for(n_positions)
        chain = []
        for _ in range(need):
            bid = self._alloc()
            if bid is None:
                for b in chain:
                    self.pool.release(b)
                raise InsufficientBlocksError(
                    f"pool dry: {need} block(s) wanted, "
                    f"{self.pool.num_free} free")
            chain.append(bid)
        self._install(slot, chain)
        obstrace.instant("kv.seat", slot=slot, blocks=len(chain),
                         free=self.pool.num_free)
        return chain

    def seat_shared(self, slot, chain, n_positions):
        """Seat a prefix-cache hit: take shared references on
        ``chain[:blocks_for(n_positions)]`` — no prefill, no copy; the
        first divergent write triggers the copy-on-write fork in
        ``write_plan``."""
        take = [self.pool.share(b)
                for b in chain[:self.blocks_for(n_positions)]]
        self._install(slot, take)
        obstrace.instant("kv.seat_shared", slot=slot, blocks=len(take),
                         free=self.pool.num_free)
        return take

    def _install(self, slot, chain):
        if self._chains[slot]:
            raise RuntimeError(f"slot {slot} already holds a chain")
        self._chains[slot] = chain
        self.tables[slot, :len(chain)] = chain
        self._seq += 1
        self._seat_seq[slot] = self._seq

    def register_prefix(self, tokens, slot):
        """Publish the seated slot's full-block prompt prefixes into the
        index (no-op with the prefix cache off)."""
        if self.index is not None:
            self.index.register(tokens, self._chains[slot])

    def lookup_prefix(self, tokens):
        if self.index is None:
            return 0, []
        return self.index.lookup(tokens)

    # ------------------------------------------------------ host-tier restore

    def claim_pending(self, key, n_positions):
        """Claim ``blocks_for(n_positions)`` fresh blocks for an async
        host-tier restore of prefix ``key`` — held in the pending ledger
        (refcount 1, outside every slot chain) until the transfer lands.
        All-or-nothing like ``seat_fresh``; raises
        ``InsufficientBlocksError`` leaving nothing claimed."""
        key = tuple(int(t) for t in key)
        if key in self._pending:
            raise RuntimeError(f"restore of {len(key)}-token prefix "
                               "already in flight")
        need = self.blocks_for(n_positions)
        chain = []
        for _ in range(need):
            bid = self._alloc()
            if bid is None:
                for b in chain:
                    self.pool.release(b)
                raise InsufficientBlocksError(
                    f"pool dry claiming {need} block(s) for a host-tier "
                    f"restore ({self.pool.num_free} free)")
            chain.append(bid)
        self._pending[key] = chain
        obstrace.instant("kv.restore_claim", blocks=len(chain),
                         free=self.pool.num_free)
        return list(chain)

    def release_pending(self, key):
        """Drop a claim whose restore died (job failure or a stale
        epoch that was caught before the state was replaced)."""
        chain = self._pending.pop(tuple(int(t) for t in key), None)
        if chain:
            for bid in chain:
                self.pool.release(bid)

    def commit_pending(self, key, covered):
        """The restore landed (the engine wrote every staged chunk into
        the claimed blocks): publish the chain into the prefix index —
        the entry takes its own references, exactly like a chain a slot
        registered — and drop the pending claim.  If the key was
        recomputed into the index while the transfer flew, the existing
        entry wins (identical K/V by determinism) and the restored
        blocks simply free."""
        key = tuple(int(t) for t in key)
        chain = self._pending.pop(key)
        if self.index is not None:
            self.index._add(key, int(covered), chain)
        for bid in chain:
            self.pool.release(bid)

    # ------------------------------------------------------------ stepping

    def write_plan(self, slot, position):
        """Make ``position`` writable for ``slot`` before the next step.
        Returns None (already exclusive), ``("alloc", j, bid)`` (chain
        grew into a fresh block), or ``("cow", j, src, dst)`` — the
        engine must device-copy block ``src`` into ``dst`` (the
        copy-on-write fork; ``src`` stays resident for its other
        sharers).  Raises ``InsufficientBlocksError`` when the pool is
        dry — the engine preempts a victim slot and retries."""
        j = position // self.block_size
        chain = self._chains[slot]
        if j > len(chain):
            raise RuntimeError(
                f"slot {slot} chain has {len(chain)} block(s) but writes "
                f"block {j}: positions were skipped")
        if j == len(chain):
            bid = self._alloc()
            if bid is None:
                raise InsufficientBlocksError(
                    f"pool dry growing slot {slot} to block {j}")
            chain.append(bid)
            self.tables[slot, j] = bid
            return ("alloc", j, bid)
        src = chain[j]
        if self.pool.refcount(src) == 1:
            return None
        dst = self._alloc()
        if self.pool.refcount(src) == 1:
            # _alloc's LRU evictions dropped the last OTHER reference
            # (the sharer was the index): the block is exclusive after
            # all — no fork, and a request sized to fit the pool alone
            # never dies here
            if dst is not None:
                self.pool.release(dst)
            return None
        if dst is None:
            raise InsufficientBlocksError(
                f"pool dry forking shared block {src} for slot {slot}")
        self.pool.release(src)      # our reference moves to the fork
        chain[j] = dst
        self.tables[slot, j] = dst
        return ("cow", j, src, dst)

    def truncate(self, slot, n_positions):
        """Roll back ``slot``'s chain to the blocks covering
        ``[0, n_positions)`` — the speculative-decoding rejection path
        (docs/serving.md "Speculative decoding"): ``prepare_step``
        provisioned blocks for the whole drafted span before the verify
        step, but acceptance committed fewer positions, so the tail
        blocks past the committed span release back to the pool.  Their
        contents need no scrubbing: the attention mask stops at each
        lane's own position, and a later write into those positions
        re-provisions a block and overwrites it in the same step that
        first unmasks it.  A shared tail block (possible when a prefix
        seat over-covered) only drops this slot's reference.  Returns
        the number of blocks released."""
        keep = self.blocks_for(n_positions)
        chain = self._chains[slot]
        dropped = 0
        while len(chain) > keep:
            bid = chain.pop()
            self.tables[slot, len(chain)] = SCRATCH_BLOCK
            self.pool.release(bid)
            dropped += 1
        if dropped:
            obstrace.instant("kv.truncate", slot=slot, blocks=dropped,
                             free=self.pool.num_free)
        return dropped

    def victim(self, exclude):
        """Youngest active slot outside ``exclude`` (pool-pressure
        preemption order), or None."""
        best, best_seq = None, -1
        for s, chain in enumerate(self._chains):
            if chain and s not in exclude \
                    and self._seat_seq[s] > best_seq:
                best, best_seq = s, self._seat_seq[s]
        return best

    # ------------------------------------------------------------ teardown

    def evict(self, slot):
        """Release the slot's chain (shared blocks stay resident for
        their other sharers / the index) and zero its table row."""
        for bid in self._chains[slot]:
            self.pool.release(bid)
        self._chains[slot] = []
        self.tables[slot, :] = SCRATCH_BLOCK

    def check(self):
        """Full ledger audit: every block's refcount equals the number
        of slot-chain plus index references to it (no leak, no double
        count), and the pool's own free/held partition holds."""
        self.pool.check()
        expect = collections.Counter()
        for chain in self._chains:
            expect.update(chain)
        for chain in self._pending.values():
            expect.update(chain)
        if self.index is not None:
            for _cov, chain in self.index._entries.values():
                expect.update(chain)
        for bid in range(1, self.pool.num_blocks):
            if self.pool.refcount(bid) != expect.get(bid, 0):
                raise AssertionError(
                    f"block {bid}: refcount {self.pool.refcount(bid)} != "
                    f"{expect.get(bid, 0)} ledger references")
