"""Paged KV cache: block-pool allocator + copy-on-write prefix sharing.

The slab layout (``DecodeEngine(kv_layout="slab")``) reserves
``max_len`` KV positions per slot no matter how long the request
actually runs — the reservation waste PagedAttention (vLLM, SOSP'23)
eliminates — and at fleet scale most traffic shares a handful of
system-prompt prefixes the slab recomputes and stores once PER SLOT.
This module is the host half of the paged answer (docs/serving.md §5):

* ``BlockPool`` — a fixed pool of ``num_blocks`` KV blocks of
  ``block_size`` positions each (the device arrays live in the engine:
  per-layer ``[num_blocks, block_size, Dkv]``,
  ``transformer.init_lm_cache_paged``).  Free-list allocation with
  per-block REFCOUNTS: a physical block referenced by several slot
  chains (and/or the prefix index) stays resident until the last
  reference releases it.  Block 0 is reserved as the scratch block free
  slot rows point at; allocatable ids are ``1..num_blocks-1``.

* ``PrefixIndex`` — maps block-aligned prompt prefixes (token tuples of
  length ``k * block_size``) to the already-resident block chains that
  hold their K/V.  A new request whose prompt starts with a cached
  prefix admits by TAKING REFERENCES to those physical blocks instead
  of re-prefilling them: duplicate KV bytes and duplicate prefill
  compute both disappear.  LRU: under pool pressure the allocator
  evicts the stalest entries (their blocks free once no slot shares
  them).

* ``PagedKVState`` — per-engine bookkeeping tying the two together:
  the per-slot block tables (``[num_slots, blocks_per_row]`` int32 fed
  to the jitted step as DATA — churn never retraces), per-slot chain
  ledgers, and the write-exclusivity rule that yields COPY-ON-WRITE: a
  slot about to write into a block whose refcount exceeds 1 first forks
  it (the engine device-copies the block, the table entry swaps to the
  private copy) so shared prefix blocks are physically immutable while
  referenced.

Everything here is host-side numpy/bookkeeping between steps; the one
jitted step (``transformer.lm_decode_step_paged``) only ever sees
fixed-shape pools and tables.  ``check()`` verifies the refcount ledger
(no leak, no double-free) — the chaos tests run it after every fault
matrix pass.
"""

import collections

import numpy as np

from paddle_tpu.obs import trace as obstrace
from paddle_tpu.utils.error import ConfigError

SCRATCH_BLOCK = 0


def slab_equivalent_blocks(num_slots, max_len, block_size,
                           kv_dtype="float32", mesh_shards=1):
    """Auto pool size (``DecodeEngine(kv_num_blocks=0)``) at the SLAB-
    EQUIVALENT **per-chip** byte budget: an f32 pool gets exactly the
    slab's ``num_slots * ceil(max_len / block_size)`` blocks (same KV
    bytes, strictly more packable).  ``kv_dtype="int8"`` DOUBLES the
    block count inside that same budget: an int8 block plus its f32
    per-(position, head) scale sidecar costs ``(1/4 + 1/head_dim)`` of
    the f32 block's bytes (quant/kv.kv_bytes_per_position), i.e. at
    most half for head_dim >= 4 — so twice the blocks still fit, with
    headroom that grows with head_dim.  ``mesh_shards=n`` (the sharded
    decode mesh, docs/serving.md "Sharded decode") MULTIPLIES by n: a
    chip holds only its ``Hkv/n`` head stripe of each block, so the
    single-chip per-chip budget holds n× the block count — the capacity
    win tensor-parallel serving exists for.  +1 everywhere for the
    reserved scratch block 0."""
    per_row = -(-int(max_len) // int(block_size))
    blocks = int(num_slots) * per_row
    if kv_dtype == "int8":
        blocks *= 2
    blocks *= max(1, int(mesh_shards))
    return blocks + 1


class InsufficientBlocksError(RuntimeError):
    """The pool cannot supply the requested blocks even after evicting
    every prefix-index entry.  Admission defers the request (it is NOT a
    client error); mid-decode the engine preempts a victim slot instead
    (``evictions{reason="pool_exhausted"}``)."""


class BlockPool:
    """Free-list + refcount allocator over ``num_blocks`` KV blocks.

    ``alloc()`` hands out a block at refcount 1; ``share()`` adds a
    reference (a second slot chain or a prefix-index entry);
    ``release()`` drops one and returns the block to the free list at
    zero.  All host-side integers — the device arrays are the engine's.
    """

    def __init__(self, num_blocks, block_size):
        if num_blocks < 2:
            raise ConfigError("BlockPool needs num_blocks >= 2 (block 0 "
                              "is the reserved scratch block)")
        if block_size < 1:
            raise ConfigError("BlockPool needs block_size >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # pop() -> block 1 first; scratch block 0 is never allocatable
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = np.zeros((self.num_blocks,), np.int64)

    @property
    def num_allocatable(self):
        return self.num_blocks - 1

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_used(self):
        return self.num_allocatable - len(self._free)

    def refcount(self, bid):
        return int(self._ref[bid])

    def alloc(self):
        """One free block at refcount 1, or None when the pool is dry
        (callers then evict prefix-index entries / preempt a slot)."""
        if not self._free:
            return None
        bid = self._free.pop()
        self._ref[bid] = 1
        return bid

    def share(self, bid):
        if self._ref[bid] < 1:
            raise RuntimeError(f"BlockPool.share of unowned block {bid}")
        self._ref[bid] += 1
        return bid

    def release(self, bid):
        if self._ref[bid] < 1:
            raise RuntimeError(f"BlockPool.release of free block {bid} "
                               "(double free)")
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)

    def check(self):
        """Internal-consistency invariants: the free list and the
        refcounts partition the allocatable ids exactly.  Raises on any
        violation (leak or double-free would break one)."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("free list holds duplicates: "
                                 f"{sorted(self._free)}")
        if SCRATCH_BLOCK in free or self._ref[SCRATCH_BLOCK] != 0:
            raise AssertionError("scratch block 0 entered the allocator")
        held = {int(b) for b in np.nonzero(self._ref)[0]}
        if free & held:
            raise AssertionError(f"blocks both free and referenced: "
                                 f"{sorted(free & held)}")
        if len(free) + len(held) != self.num_allocatable:
            raise AssertionError(
                f"leaked blocks: {self.num_allocatable} allocatable != "
                f"{len(free)} free + {len(held)} held")


class PrefixIndex:
    """Prompt prefix -> resident block chain, LRU.

    Two key kinds share one map: every BLOCK-ALIGNED prefix of a
    registered prompt (token tuples of length ``k * block_size`` —
    reusable by any prompt sharing those leading blocks), plus the EXACT
    full prompt when its tail block is partial (reusable by EXACT
    duplicates only — ``lookup`` probes the exact key and block-aligned
    prefixes, so a LONGER probe sharing this prompt matches just the
    aligned portion — the seat then lands INSIDE the shared tail block
    and the first write copy-on-write forks it).  An entry holds ONE
    pool reference per block, so the
    chain outlives the slot that prefilled it.  ``lookup`` returns the
    LONGEST registered coverage of the probe (and refreshes its LRU
    position); ``evict_lru`` releases the stalest entry's references —
    the blocks actually free only once no slot chain shares them.
    """

    def __init__(self, pool):
        self._pool = pool
        self._entries = collections.OrderedDict()  # key -> (covered, [bids])

    def __len__(self):
        return len(self._entries)

    @property
    def block_refs(self):
        """Total (entry, block) references the index holds — the ledger
        term ``PagedKVState.check`` audits."""
        return sum(len(c) for _cov, c in self._entries.values())

    def _add(self, key, covered, blocks):
        if key in self._entries:
            # existing entries win — their blocks hold identical K/V by
            # determinism, and keeping them preserves their sharers
            self._entries.move_to_end(key)
            return
        self._entries[key] = (covered, [self._pool.share(b)
                                        for b in blocks])

    def register(self, tokens, chain):
        """Publish ``tokens`` (the real prefix of a just-admitted
        prompt, whose K/V ``chain`` holds): every block-aligned prefix,
        plus the exact full key when the tail block is partial."""
        bs = self._pool.block_size
        toks = tuple(int(t) for t in tokens)
        for m in range(1, len(toks) // bs + 1):
            self._add(toks[:m * bs], m * bs, chain[:m])
        if len(toks) % bs:
            self._add(toks, len(toks),
                      chain[:-(-len(toks) // bs)])

    def lookup(self, tokens):
        """Longest registered coverage of ``tokens``: the exact probe
        first (duplicate prompt — covers its partial tail too), then
        block-aligned prefixes descending.  Returns
        ``(covered_positions, [bids])`` or ``(0, [])``.  The hit is an
        LRU touch; references are NOT taken here — seating does."""
        bs = self._pool.block_size
        toks = tuple(int(t) for t in tokens)
        ent = self._entries.get(toks)
        if ent is not None:
            self._entries.move_to_end(toks)
            return ent[0], list(ent[1])
        for m in range(len(toks) // bs, 0, -1):
            ent = self._entries.get(toks[:m * bs])
            if ent is not None:
                self._entries.move_to_end(toks[:m * bs])
                return ent[0], list(ent[1])
        return 0, []

    def evict_lru(self):
        """Release the stalest entry's block references; True if one was
        evicted."""
        if not self._entries:
            return False
        _key, (_cov, chain) = self._entries.popitem(last=False)
        for bid in chain:
            self._pool.release(bid)
        return True

    def clear(self):
        while self.evict_lru():
            pass


class PagedKVState:
    """Host bookkeeping for one paged ``DecodeEngine``: pool + prefix
    index + per-slot block tables/chains + the write-exclusivity plan.

    The engine owns every device operation (the jitted step, block
    write, block copy); this object only decides WHICH blocks — methods
    that need a device copy return the plan and the engine executes it.
    """

    def __init__(self, num_slots, num_blocks, block_size, max_len,
                 prefix_cache=True):
        self.pool = BlockPool(num_blocks, block_size)
        self.index = PrefixIndex(self.pool) if prefix_cache else None
        self.block_size = self.pool.block_size
        self.blocks_per_row = -(-int(max_len) // self.block_size)
        self.tables = np.zeros((int(num_slots), self.blocks_per_row),
                               np.int32)
        self._chains = [[] for _ in range(int(num_slots))]
        # admission order, for pool-pressure victim choice (youngest
        # first: cheapest replay, most blocks still ahead of it)
        self._seat_seq = np.zeros((int(num_slots),), np.int64)
        self._seq = 0

    # ------------------------------------------------------------ sizing

    def blocks_for(self, n_positions):
        return -(-int(n_positions) // self.block_size)

    def can_admit(self, n_positions):
        """Could ``blocks_for(n_positions)`` blocks be produced right
        now (free list + whatever evicting the whole prefix index would
        release)?  Conservative: index blocks shared by live slots are
        counted as unevictable."""
        need = self.blocks_for(n_positions)
        free = self.pool.num_free
        if free >= need:
            return True
        if self.index is None:
            return False
        live = {b for c in self._chains for b in c}
        evictable = {b for _cov, chain in self.index._entries.values()
                     for b in chain
                     if b not in live and self.pool.refcount(b) >= 1}
        return free + len(evictable) >= need

    def _alloc(self):
        """One block, evicting LRU prefix entries under pressure;
        None when truly dry (the caller preempts a slot)."""
        bid = self.pool.alloc()
        while bid is None and self.index is not None \
                and self.index.evict_lru():
            bid = self.pool.alloc()
        return bid

    # ------------------------------------------------------------ seating

    def seat_fresh(self, slot, n_positions):
        """Claim private blocks covering ``[0, n_positions)`` for a
        just-prefilled admission; returns the chain (the engine writes
        the prefill rows into them).  All-or-nothing: on exhaustion
        nothing is claimed and ``InsufficientBlocksError`` raises (the
        batcher defers the request)."""
        need = self.blocks_for(n_positions)
        chain = []
        for _ in range(need):
            bid = self._alloc()
            if bid is None:
                for b in chain:
                    self.pool.release(b)
                raise InsufficientBlocksError(
                    f"pool dry: {need} block(s) wanted, "
                    f"{self.pool.num_free} free")
            chain.append(bid)
        self._install(slot, chain)
        obstrace.instant("kv.seat", slot=slot, blocks=len(chain),
                         free=self.pool.num_free)
        return chain

    def seat_shared(self, slot, chain, n_positions):
        """Seat a prefix-cache hit: take shared references on
        ``chain[:blocks_for(n_positions)]`` — no prefill, no copy; the
        first divergent write triggers the copy-on-write fork in
        ``write_plan``."""
        take = [self.pool.share(b)
                for b in chain[:self.blocks_for(n_positions)]]
        self._install(slot, take)
        obstrace.instant("kv.seat_shared", slot=slot, blocks=len(take),
                         free=self.pool.num_free)
        return take

    def _install(self, slot, chain):
        if self._chains[slot]:
            raise RuntimeError(f"slot {slot} already holds a chain")
        self._chains[slot] = chain
        self.tables[slot, :len(chain)] = chain
        self._seq += 1
        self._seat_seq[slot] = self._seq

    def register_prefix(self, tokens, slot):
        """Publish the seated slot's full-block prompt prefixes into the
        index (no-op with the prefix cache off)."""
        if self.index is not None:
            self.index.register(tokens, self._chains[slot])

    def lookup_prefix(self, tokens):
        if self.index is None:
            return 0, []
        return self.index.lookup(tokens)

    # ------------------------------------------------------------ stepping

    def write_plan(self, slot, position):
        """Make ``position`` writable for ``slot`` before the next step.
        Returns None (already exclusive), ``("alloc", j, bid)`` (chain
        grew into a fresh block), or ``("cow", j, src, dst)`` — the
        engine must device-copy block ``src`` into ``dst`` (the
        copy-on-write fork; ``src`` stays resident for its other
        sharers).  Raises ``InsufficientBlocksError`` when the pool is
        dry — the engine preempts a victim slot and retries."""
        j = position // self.block_size
        chain = self._chains[slot]
        if j > len(chain):
            raise RuntimeError(
                f"slot {slot} chain has {len(chain)} block(s) but writes "
                f"block {j}: positions were skipped")
        if j == len(chain):
            bid = self._alloc()
            if bid is None:
                raise InsufficientBlocksError(
                    f"pool dry growing slot {slot} to block {j}")
            chain.append(bid)
            self.tables[slot, j] = bid
            return ("alloc", j, bid)
        src = chain[j]
        if self.pool.refcount(src) == 1:
            return None
        dst = self._alloc()
        if self.pool.refcount(src) == 1:
            # _alloc's LRU evictions dropped the last OTHER reference
            # (the sharer was the index): the block is exclusive after
            # all — no fork, and a request sized to fit the pool alone
            # never dies here
            if dst is not None:
                self.pool.release(dst)
            return None
        if dst is None:
            raise InsufficientBlocksError(
                f"pool dry forking shared block {src} for slot {slot}")
        self.pool.release(src)      # our reference moves to the fork
        chain[j] = dst
        self.tables[slot, j] = dst
        return ("cow", j, src, dst)

    def truncate(self, slot, n_positions):
        """Roll back ``slot``'s chain to the blocks covering
        ``[0, n_positions)`` — the speculative-decoding rejection path
        (docs/serving.md "Speculative decoding"): ``prepare_step``
        provisioned blocks for the whole drafted span before the verify
        step, but acceptance committed fewer positions, so the tail
        blocks past the committed span release back to the pool.  Their
        contents need no scrubbing: the attention mask stops at each
        lane's own position, and a later write into those positions
        re-provisions a block and overwrites it in the same step that
        first unmasks it.  A shared tail block (possible when a prefix
        seat over-covered) only drops this slot's reference.  Returns
        the number of blocks released."""
        keep = self.blocks_for(n_positions)
        chain = self._chains[slot]
        dropped = 0
        while len(chain) > keep:
            bid = chain.pop()
            self.tables[slot, len(chain)] = SCRATCH_BLOCK
            self.pool.release(bid)
            dropped += 1
        if dropped:
            obstrace.instant("kv.truncate", slot=slot, blocks=dropped,
                             free=self.pool.num_free)
        return dropped

    def victim(self, exclude):
        """Youngest active slot outside ``exclude`` (pool-pressure
        preemption order), or None."""
        best, best_seq = None, -1
        for s, chain in enumerate(self._chains):
            if chain and s not in exclude \
                    and self._seat_seq[s] > best_seq:
                best, best_seq = s, self._seat_seq[s]
        return best

    # ------------------------------------------------------------ teardown

    def evict(self, slot):
        """Release the slot's chain (shared blocks stay resident for
        their other sharers / the index) and zero its table row."""
        for bid in self._chains[slot]:
            self.pool.release(bid)
        self._chains[slot] = []
        self.tables[slot, :] = SCRATCH_BLOCK

    def check(self):
        """Full ledger audit: every block's refcount equals the number
        of slot-chain plus index references to it (no leak, no double
        count), and the pool's own free/held partition holds."""
        self.pool.check()
        expect = collections.Counter()
        for chain in self._chains:
            expect.update(chain)
        if self.index is not None:
            for _cov, chain in self.index._entries.values():
                expect.update(chain)
        for bid in range(1, self.pool.num_blocks):
            if self.pool.refcount(bid) != expect.get(bid, 0):
                raise AssertionError(
                    f"block {bid}: refcount {self.pool.refcount(bid)} != "
                    f"{expect.get(bid, 0)} ledger references")
