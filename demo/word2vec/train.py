"""word2vec / neural n-gram LM (reference demo/word2vec + imikolov
dataset): 4-gram context -> shared embeddings -> hidden -> hsigmoid or
softmax over the vocab."""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import integer_value
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.data.datasets import imikolov

EMB = 32
N = 5   # n-gram order


def get_config(use_hsigmoid=True):
    vocab = imikolov.WORD_DIM
    words = [L.data_layer(f"w{i}", size=vocab) for i in range(N - 1)]
    target = L.data_layer("target", size=1)
    embs = [L.embedding_layer(w, size=EMB,
                              param_attr={"name": "emb"}) for w in words]
    ctx = L.concat_layer(embs)
    hidden = L.fc_layer(ctx, size=128, act="sigmoid")
    if use_hsigmoid:
        cost = L.hsigmoid(hidden, target, num_classes=vocab)
        output = hidden
    else:
        pred = L.fc_layer(hidden, size=vocab, act="softmax")
        cost = L.classification_cost(pred, target)
        output = pred
    feeding = {f"w{i}": integer_value(vocab) for i in range(N - 1)}
    feeding["target"] = integer_value(vocab)
    return {
        "cost": cost,
        "output": output,
        "optimizer": optim.AdaGrad(learning_rate=0.1),
        "train_reader": reader_mod.batch(imikolov.train(n=N), 64),
        "feeding": feeding,
    }


if __name__ == "__main__":
    from paddle_tpu.trainer import SGD
    cfg = get_config()
    SGD(cost=cfg["cost"], update_equation=cfg["optimizer"]).train(
        cfg["train_reader"], num_passes=2, feeding=cfg["feeding"],
        log_period=50)
