"""Linear regression introduction (reference demo/introduction
trainer_config.py: y = wx + b on synthetic y = 2x + 0.3)."""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import dense_vector
from paddle_tpu.data import reader as reader_mod


def _synthetic(n=1024, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (n, 1)).astype(np.float32)
    y = (2.0 * x + 0.3 + 0.05 * rng.randn(n, 1)).astype(np.float32)

    def reader():
        for i in range(n):
            yield x[i], y[i]
    return reader


def get_config():
    x = L.data_layer("x", size=1)
    y = L.data_layer("y", size=1)
    pred = L.fc_layer(x, size=1, act=None, name="fc")
    cost = L.regression_cost(pred, y)
    return {
        "cost": cost,
        "output": pred,
        "optimizer": optim.Momentum(learning_rate=0.1, momentum=0.9),
        "train_reader": reader_mod.batch(_synthetic(), 64),
        "feeding": {"x": dense_vector(1), "y": dense_vector(1)},
    }


if __name__ == "__main__":
    from paddle_tpu.trainer import SGD
    cfg = get_config()
    tr = SGD(cost=cfg["cost"], update_equation=cfg["optimizer"])
    tr.train(cfg["train_reader"], num_passes=8, feeding=cfg["feeding"],
             log_period=10)
    w = np.asarray(tr.parameters["fc"]["w0"]).ravel()[0]
    b = np.asarray(tr.parameters["fc"]["b"]).ravel()[0]
    print(f"learned y = {w:.3f}x + {b:.3f} (target 2x + 0.3)")
