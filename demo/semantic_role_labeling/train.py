"""Semantic role labeling with a deep bidirectional LSTM + CRF (reference
demo/semantic_role_labeling db_lstm: 8-layer alternating-direction LSTM
over word/predicate/context features, CRF cost)."""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import integer_value_sequence
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.data.datasets import conll05

EMB = 32
HID = 64
DEPTH = 4   # reference uses 8; 4 keeps the demo quick


def get_config():
    num_labels = conll05.NUM_LABELS
    words = L.data_layer("words", size=conll05.WORD_DICT, is_seq=True)
    preds = L.data_layer("preds", size=conll05.PRED_DICT, is_seq=True)
    label = L.data_layer("label", size=1, is_seq=True)

    word_emb = L.embedding_layer(words, size=EMB)
    pred_emb = L.embedding_layer(preds, size=EMB)
    feats = L.mixed_layer(size=4 * HID, input=[
        L.full_matrix_projection(word_emb),
        L.full_matrix_projection(pred_emb),
    ], act=None)

    # alternating-direction stacked LSTM (db-LSTM)
    lstm = L.lstmemory(feats, size=HID, reverse=False)
    inputs = [feats, lstm]
    for depth in range(1, DEPTH):
        mix = L.mixed_layer(size=4 * HID, input=[
            L.full_matrix_projection(inputs[-1]),
            L.full_matrix_projection(inputs[-2]),
        ], act=None)
        lstm = L.lstmemory(mix, size=HID, reverse=(depth % 2 == 1))
        inputs.append(mix)
        inputs.append(lstm)

    emission = L.mixed_layer(size=num_labels, input=[
        L.full_matrix_projection(inputs[-2]),
        L.full_matrix_projection(inputs[-1]),
    ], act=None)
    crf_cost = L.crf_layer(emission, label, size=num_labels, name="crf")
    decoded = L.crf_decoding_layer(emission, size=num_labels,
                                   param_name=crf_cost.cfg["param_name"])
    return {
        "cost": crf_cost,
        "output": decoded,
        "optimizer": optim.Adam(learning_rate=1e-3, clip_threshold=5.0),
        "train_reader": reader_mod.batch(conll05.train(), 16),
        "feeding": {
            "words": integer_value_sequence(conll05.WORD_DICT),
            "preds": integer_value_sequence(conll05.PRED_DICT),
            "label": integer_value_sequence(num_labels),
        },
    }


if __name__ == "__main__":
    from paddle_tpu.trainer import SGD
    cfg = get_config()
    SGD(cost=cfg["cost"], update_equation=cfg["optimizer"]).train(
        cfg["train_reader"], num_passes=2, feeding=cfg["feeding"],
        log_period=20)
