"""Attention NMT (reference demo/seqToseq seqToseq_net.py) — functional
flagship model; supports training and beam-search generation.

Train:    python demo/seqToseq/train.py
Generate: python demo/seqToseq/train.py --generate --model_dir output
"""

import argparse
import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import pad_sequences
from paddle_tpu.models import seq2seq
from paddle_tpu import optim
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.data.datasets import wmt14
from paddle_tpu.trainer.checkpoint import save_checkpoint, load_checkpoint
from paddle_tpu.utils.logging import logger


def make_batches(batch_size=32):
    return reader_mod.batch(
        reader_mod.shuffle(wmt14.train(), 1024, seed=0), batch_size)


def feed_batch(batch):
    src = pad_sequences([np.asarray(b[0], np.int32) for b in batch])
    trg_in = pad_sequences([np.asarray(b[1], np.int32) for b in batch])
    trg_next = pad_sequences([np.asarray(b[2], np.int32) for b in batch])
    return src, trg_in, trg_next


def train(num_passes=2, save_dir="output", hidden=256, emb=256):
    params = seq2seq.init(jax.random.PRNGKey(0),
                          src_vocab=wmt14.SRC_DICT_SIZE,
                          trg_vocab=wmt14.TRG_DICT_SIZE,
                          emb_dim=emb, hidden=hidden)
    opt = optim.Adam(learning_rate=5e-4, clip_norm=5.0)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, src, trg_in, trg_next):
        loss, grads = jax.value_and_grad(seq2seq.loss)(params, src, trg_in,
                                                       trg_next)
        params, opt_state = opt.update(grads, opt_state, params)
        return params, opt_state, loss

    for pass_id in range(num_passes):
        losses = []
        for i, batch in enumerate(make_batches()()):
            src, trg_in, trg_next = feed_batch(batch)
            params, opt_state, loss = step(params, opt_state, src, trg_in,
                                           trg_next)
            losses.append(float(loss))
            if (i + 1) % 10 == 0:
                logger.info("pass %d batch %d loss %.4f", pass_id, i + 1,
                            np.mean(losses[-10:]))
        save_checkpoint(save_dir, pass_id, params)
    return params


def generate(model_dir, beam_size=5, max_len=40):
    params, _, _, _ = load_checkpoint(model_dir)
    batch = list(__import__("itertools").islice(wmt14.test()(), 8))
    src, _, _ = feed_batch(batch)
    res = seq2seq.generate(params, src, beam_size=beam_size, max_len=max_len,
                           bos_id=wmt14.START, eos_id=wmt14.END)
    for i in range(src.data.shape[0]):
        hyp = [int(t) for t in np.asarray(res.tokens[i, 0])
               [:int(res.lengths[i, 0])]]
        print(f"src={list(map(int, np.asarray(src.data[i])[:int(src.lengths[i])]))}")
        print(f"  -> {hyp} (score {float(res.scores[i, 0]):.3f})")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--generate", action="store_true")
    ap.add_argument("--model_dir", default="output")
    ap.add_argument("--num_passes", type=int, default=2)
    args = ap.parse_args()
    if args.generate:
        generate(args.model_dir)
    else:
        train(args.num_passes, args.model_dir)
