"""Attention GRU encoder-decoder network in the v1 config DSL (py3 port of
the reference demo/seqToseq/seqToseq_net.py — the framework-parity demo for
recurrent_group + memory + beam_search generation).

Structure (reference :78-205): bidirectional GRU encoder, Bahdanau attention
inside a recurrent_group decoder (memory-linked gru_step), softmax over the
target vocabulary; generation mode swaps the target input for a
GeneratedInput and runs beam_search with shared step-layer names.
"""

import os

from paddle.trainer_config_helpers import *


def seq_to_seq_data(data_dir, is_generating, dict_size=None,
                    train_list="train.list", test_list="test.list",
                    gen_list="gen.list", gen_result="gen_result"):
    src_dict_path = os.path.join(data_dir, "src.dict")
    trg_dict_path = os.path.join(data_dir, "trg.dict")
    define_py_data_sources2(
        train_list=None if is_generating else os.path.join(data_dir,
                                                           train_list),
        test_list=os.path.join(data_dir,
                               gen_list if is_generating else test_list),
        module="dataprovider",
        obj="process",
        args={"src_dict_path": src_dict_path,
              "trg_dict_path": trg_dict_path,
              "is_generating": is_generating})
    return {"src_dict_path": src_dict_path, "trg_dict_path": trg_dict_path,
            "gen_result": gen_result}


def gru_encoder_decoder(data_conf, is_generating, word_vector_dim=512,
                        encoder_size=512, decoder_size=512, beam_size=3,
                        max_length=250, error_clipping=50):
    src_dict_dim = len(open(data_conf["src_dict_path"]).readlines())
    trg_dict_dim = len(open(data_conf["trg_dict_path"]).readlines())
    clip = ExtraLayerAttribute(error_clipping_threshold=error_clipping)

    src_word = data_layer(name="source_language_word", size=src_dict_dim)
    src_emb = embedding_layer(
        input=src_word, size=word_vector_dim,
        param_attr=ParamAttr(name="_source_language_embedding"))
    enc_fwd = simple_gru(input=src_emb, size=encoder_size, naive=True,
                         gru_layer_attr=clip)
    enc_bwd = simple_gru(input=src_emb, size=encoder_size, reverse=True,
                         naive=True, gru_layer_attr=clip)
    encoded_vector = concat_layer(input=[enc_fwd, enc_bwd])

    with mixed_layer(size=decoder_size) as encoded_proj:
        encoded_proj += full_matrix_projection(input=encoded_vector)

    with mixed_layer(size=decoder_size, act=TanhActivation()) as decoder_boot:
        decoder_boot += full_matrix_projection(
            input=first_seq(input=enc_bwd))

    def gru_decoder_with_attention(enc_vec, enc_proj, current_word):
        decoder_mem = memory(name="gru_decoder", size=decoder_size,
                             boot_layer=decoder_boot)
        context = simple_attention(encoded_sequence=enc_vec,
                                   encoded_proj=enc_proj,
                                   decoder_state=decoder_mem)
        with mixed_layer(size=decoder_size * 3) as decoder_inputs:
            decoder_inputs += full_matrix_projection(input=context)
            decoder_inputs += full_matrix_projection(input=current_word)
        gru_step = gru_step_naive_layer(name="gru_decoder",
                                        input=decoder_inputs,
                                        output_mem=decoder_mem,
                                        size=decoder_size, layer_attr=clip)
        with mixed_layer(size=trg_dict_dim, bias_attr=True,
                         act=SoftmaxActivation()) as out:
            out += full_matrix_projection(input=gru_step)
        return out

    group_inputs = [StaticInput(input=encoded_vector, is_seq=True),
                    StaticInput(input=encoded_proj, is_seq=True)]

    if not is_generating:
        trg_emb = embedding_layer(
            input=data_layer(name="target_language_word", size=trg_dict_dim),
            size=word_vector_dim,
            param_attr=ParamAttr(name="_target_language_embedding"))
        decoder = recurrent_group(name="decoder_group",
                                  step=gru_decoder_with_attention,
                                  input=group_inputs + [trg_emb])
        label = data_layer(name="target_language_next_word",
                           size=trg_dict_dim)
        outputs(classification_cost(input=decoder, label=label))
    else:
        trg_emb = GeneratedInput(
            size=trg_dict_dim,
            embedding_name="_target_language_embedding",
            embedding_size=word_vector_dim)
        beam_gen = beam_search(name="decoder_group",
                               step=gru_decoder_with_attention,
                               input=group_inputs + [trg_emb],
                               bos_id=0, eos_id=1, beam_size=beam_size,
                               max_length=max_length)
        outputs(beam_gen)
