"""WMT14-style seqToseq data provider (v1-config port of the reference
demo/seqToseq/dataprovider.py — py3 syntax; same slot names and semantics)."""

from paddle.trainer.PyDataProvider2 import *

UNK_IDX = 2
START = "<s>"
END = "<e>"


def hook(settings, src_dict_path, trg_dict_path, is_generating, file_list,
         **kwargs):
    settings.job_mode = not is_generating

    def load_dict(path):
        with open(path) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    settings.src_dict = load_dict(src_dict_path)
    settings.trg_dict = load_dict(trg_dict_path)

    if settings.job_mode:
        settings.input_types = {
            "source_language_word":
                integer_value_sequence(len(settings.src_dict)),
            "target_language_word":
                integer_value_sequence(len(settings.trg_dict)),
            "target_language_next_word":
                integer_value_sequence(len(settings.trg_dict)),
        }
    else:
        settings.input_types = {
            "source_language_word":
                integer_value_sequence(len(settings.src_dict)),
            "sent_id":
                integer_value_sequence(
                    len(open(file_list[0]).readlines()) if file_list else 1),
        }


def _ids(sentence, dictionary):
    return ([dictionary[START]]
            + [dictionary.get(w, UNK_IDX) for w in sentence.strip().split()]
            + [dictionary[END]])


@provider(init_hook=hook, pool_size=50000)
def process(settings, file_name):
    with open(file_name) as f:
        for line_count, line in enumerate(f):
            fields = line.strip().split("\t")
            if settings.job_mode:
                if len(fields) != 2:
                    continue
                src_ids = _ids(fields[0], settings.src_dict)
                trg_ids = [settings.trg_dict.get(w, UNK_IDX)
                           for w in fields[1].split()]
                if len(src_ids) > 80 or len(trg_ids) > 80:
                    continue
                yield {
                    "source_language_word": src_ids,
                    "target_language_word":
                        [settings.trg_dict[START]] + trg_ids,
                    "target_language_next_word":
                        trg_ids + [settings.trg_dict[END]],
                }
            else:
                yield {"source_language_word": _ids(fields[0],
                                                    settings.src_dict),
                       "sent_id": [line_count]}
