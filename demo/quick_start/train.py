"""Text classification quick start (reference demo/quick_start: LR / CNN /
LSTM variants over bag-of-words product reviews).  Variant selected via
--config_args model=lr|cnn|lstm."""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import integer_value_sequence, integer_value
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.data.datasets import imdb

DICT_DIM = imdb.WORD_DIM


def lr_net(words, label):
    emb = L.embedding_layer(words, size=64)
    pooled = L.pooling_layer(emb, pooling_type=L.pooling.Sum)
    out = L.fc_layer(pooled, size=2, act="softmax")
    return L.classification_cost(out, label), out


def cnn_net(words, label):
    emb = L.embedding_layer(words, size=128)
    conv = L.networks.sequence_conv_pool(emb, context_len=3, hidden_size=256)
    out = L.fc_layer(conv, size=2, act="softmax")
    return L.classification_cost(out, label), out


def lstm_net(words, label):
    emb = L.embedding_layer(words, size=128)
    lstm = L.networks.simple_lstm(emb, size=128)
    pooled = L.pooling_layer(lstm, pooling_type=L.pooling.Max)
    out = L.fc_layer(pooled, size=2, act="softmax")
    return L.classification_cost(out, label), out


def get_config():
    model = globals().get("CONFIG_ARGS", {}).get("model", "cnn")
    words = L.data_layer("words", size=DICT_DIM, is_seq=True)
    label = L.data_layer("label", size=1)
    cost, out = {"lr": lr_net, "cnn": cnn_net, "lstm": lstm_net}[model](
        words, label)
    return {
        "cost": cost,
        "output": out,
        "optimizer": optim.Adam(learning_rate=0.002),
        "train_reader": reader_mod.batch(
            reader_mod.shuffle(imdb.train(), 512, seed=0), 64),
        "test_reader": reader_mod.batch(imdb.test(), 64),
        "feeding": {"words": integer_value_sequence(DICT_DIM),
                    "label": integer_value(2)},
    }
