"""MNIST LeNet demo (reference demo/mnist api_train_v2.py).

Run:  python -m paddle_tpu train --config demo/mnist/train.py --num_passes 5
or:   python demo/mnist/train.py   (standalone)
"""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle
import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import DataFeeder, dense_vector, integer_value
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.data.datasets import mnist


def network():
    img = L.data_layer("pixel", size=784, height=28, width=28)
    label = L.data_layer("label", size=1)
    conv1 = L.img_conv_layer(img, filter_size=5, num_filters=20,
                             num_channels=1, act="relu")
    pool1 = L.img_pool_layer(conv1, pool_size=2, stride=2, ceil_mode=False)
    conv2 = L.img_conv_layer(pool1, filter_size=5, num_filters=50, act="relu")
    pool2 = L.img_pool_layer(conv2, pool_size=2, stride=2, ceil_mode=False)
    fc1 = L.fc_layer(pool2, size=500, act="relu")
    out = L.fc_layer(fc1, size=10, act="softmax")
    cost = L.classification_cost(out, label)
    return cost, out


def get_config():
    cost, out = network()
    return {
        "cost": cost,
        "output": out,
        "optimizer": optim.Momentum(learning_rate=0.01, momentum=0.9),
        "train_reader": reader_mod.batch(
            reader_mod.shuffle(mnist.train(), 1024, seed=0), 128),
        "test_reader": reader_mod.batch(mnist.test(), 128),
        "feeding": {"pixel": dense_vector(784), "label": integer_value(10)},
    }


if __name__ == "__main__":
    from paddle_tpu.trainer import SGD
    cfg = get_config()
    SGD(cost=cfg["cost"], update_equation=cfg["optimizer"]).train(
        cfg["train_reader"], num_passes=3, feeding=cfg["feeding"],
        test_reader=cfg["test_reader"], log_period=10)
