"""Mixture-of-experts demo (post-reference capability; ops/moe.py +
layers.moe_layer).  A 4-expert top-2 MoE block classifies which quadrant a
2-D point is in — a task where different experts naturally specialize per
region.  Under a mesh trainer the experts shard over the 'expert' axis
(moe.expert_shardings); see __graft_entry__._dryrun_expert_parallel for
the sharded training step."""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import dense_vector, integer_value
from paddle_tpu.data import reader as reader_mod


def _synthetic(n=4096, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (n, 2)).astype(np.float32)
    label = (x[:, 0] > 0).astype(np.int32) * 2 + (x[:, 1] > 0).astype(np.int32)

    def reader():
        for i in range(n):
            yield x[i], int(label[i])
    return reader


def get_config():
    x = L.data_layer("x", size=2)
    y = L.data_layer("y", size=4)
    h = L.fc_layer(x, size=32, act="tanh")
    m = L.moe_layer(h, n_experts=4, top_k=2, expert_dim=64, name="moe")
    pred = L.fc_layer(m, size=4, act="softmax", name="out")
    return {
        "cost": L.classification_cost(pred, y),
        "output": pred,
        "optimizer": optim.Adam(learning_rate=0.01),
        "train_reader": reader_mod.batch(_synthetic(), 64),
        "feeding": {"x": dense_vector(2), "y": integer_value(4)},
    }


if __name__ == "__main__":
    from paddle_tpu.trainer import SGD
    cfg = get_config()
    tr = SGD(cost=cfg["cost"], update_equation=cfg["optimizer"])
    tr.train(cfg["train_reader"], num_passes=4, feeding=cfg["feeding"],
             log_period=20)
    # report accuracy on fresh points
    import jax.numpy as jnp
    from paddle_tpu.layers.graph import Topology
    rng = np.random.RandomState(1)
    xq = rng.uniform(-1, 1, (512, 2)).astype(np.float32)
    want = (xq[:, 0] > 0).astype(np.int32) * 2 + (xq[:, 1] > 0).astype(np.int32)
    probs = np.asarray(Topology([cfg["output"]]).apply(
        tr.parameters, {"x": jnp.asarray(xq)}, mode="test"))
    acc = (probs.argmax(-1) == want).mean()
    print(f"quadrant accuracy: {acc:.3f}")
