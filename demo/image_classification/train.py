"""CIFAR image classification (reference demo/image_classification VGG /
ResNet on CIFAR-10).  --config_args model=vgg|resnet."""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import dense_vector, integer_value
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.data.datasets import cifar


def vgg_bn_drop(img):
    return L.networks.img_conv_group(
        img, [64, 64], pool_size=2, num_channels=3, conv_with_batchnorm=True,
        conv_batchnorm_drop_rate=[0.3, 0.0])


def resnet_cifar(img, depth=32):
    # uses the DSL conv stack; the fast functional ResNet lives in
    # paddle_tpu.models.resnet
    n = (depth - 2) // 6
    net = L.img_conv_layer(img, filter_size=3, num_filters=16, num_channels=3,
                           padding=1, act=None)
    net = L.batch_norm_layer(net, act="relu")
    filters = 16
    for stage, nf in enumerate((16, 32, 64)):
        for block in range(n):
            stride = 2 if (stage > 0 and block == 0) else 1
            conv1 = L.img_conv_layer(net, filter_size=3, num_filters=nf,
                                     stride=stride, padding=1, act=None)
            bn1 = L.batch_norm_layer(conv1, act="relu")
            conv2 = L.img_conv_layer(bn1, filter_size=3, num_filters=nf,
                                     padding=1, act=None)
            bn2 = L.batch_norm_layer(conv2, act=None)
            if stride == 2 or filters != nf:
                proj = L.img_conv_layer(net, filter_size=1, num_filters=nf,
                                        stride=stride, act=None)
                net = L.addto_layer([bn2, proj], act="relu")
            else:
                net = L.addto_layer([bn2, net], act="relu")
            filters = nf
    return L.img_pool_layer(net, pool_size=8, stride=1, pool_type="avg")


def get_config():
    model = globals().get("CONFIG_ARGS", {}).get("model", "resnet")
    img = L.data_layer("image", size=3 * 32 * 32, height=32, width=32)
    label = L.data_layer("label", size=1)
    net = vgg_bn_drop(img) if model == "vgg" else resnet_cifar(img)
    out = L.fc_layer(net, size=10, act="softmax")
    cost = L.classification_cost(out, label)
    return {
        "cost": cost,
        "output": out,
        "optimizer": optim.Momentum(learning_rate=0.01, momentum=0.9,
                                    l2=1e-4),
        "train_reader": reader_mod.batch(
            reader_mod.shuffle(cifar.train10(), 1024, seed=0), 128),
        "test_reader": reader_mod.batch(cifar.test10(), 128),
        "feeding": {"image": dense_vector(3 * 32 * 32),
                    "label": integer_value(10)},
    }
