"""Traffic-speed prediction (reference demo/traffic_prediction): multi-task
training — 24 forecasting heads over a shared link embedding, each head a
4-class speed-bucket classifier; the embedding fc is SHARED across tasks via
a named ParamAttr (reference trainer_config.py `_link_vec.w`).

Data: the reference reads road-sensor CSV speed series; here a deterministic
synthetic series with the same windowing (TERM_NUM past points -> next
FORECASTING_NUM bucketized speeds) so the demo trains out of the box.  Point
PADDLE_TPU_DATA_DIR/traffic/speeds.csv at a real file (rows of
"id,speed,speed,...") to use real data."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import jax

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import dense_vector, integer_value
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.data.datasets._synth import local_path, rng_for
from paddle_tpu.trainer import SGD
from paddle_tpu.utils import logger

TERM_NUM = 24          # past time points fed as the feature window
FORECASTING_NUM = 24   # future points to predict (multi-task heads)
LABEL_VALUE_NUM = 4    # speed buckets
EMB_SIZE = 16


def speed_rows():
    """Real CSV rows if present, else synthetic periodic-plus-noise series
    bucketized into 1..4 like the reference provider's expectations."""
    path = local_path("traffic", "speeds.csv")
    if os.path.exists(path):
        with open(path) as f:
            next(f)  # header
            for line in f:
                yield [int(v) for v in line.rstrip("\r\n").split(",")[1:]]
        return
    rng = rng_for("traffic", "train")
    for _ in range(24):
        t = np.arange(400)
        base = 2.5 + 1.4 * np.sin(2 * np.pi * t / 96.0 + rng.rand() * 6.28)
        noisy = np.clip(np.round(base + 0.3 * rng.randn(t.size)), 1,
                        LABEL_VALUE_NUM)
        yield [int(v) for v in noisy]


def samples():
    """Sliding windows (reference dataprovider.process): feature = previous
    TERM_NUM speeds (float), labels = next FORECASTING_NUM buckets - 1."""
    for speeds in speed_rows():
        for i in range(TERM_NUM, len(speeds) - FORECASTING_NUM):
            feat = [float(v) for v in speeds[i - TERM_NUM:i]]
            labels = [v - 1 for v in speeds[i:i + FORECASTING_NUM]]
            yield tuple([feat] + labels)


def get_config():
    link_encode = L.data_layer("link_encode", size=TERM_NUM)
    costs, outputs, feeding = [], [], {"link_encode": dense_vector(TERM_NUM)}
    for i in range(FORECASTING_NUM):
        # every task shares the same link embedding weight (reference
        # ParamAttr(name='_link_vec.w'))
        link_vec = L.fc_layer(link_encode, size=EMB_SIZE, act="tanh",
                              param_attr={"name": "_link_vec.w"})
        score = L.fc_layer(link_vec, size=LABEL_VALUE_NUM, act="softmax",
                           name=f"score_{(i + 1) * 5}min")
        lab_name = f"label_{(i + 1) * 5}min"
        label = L.data_layer(lab_name, size=1)
        feeding[lab_name] = integer_value(LABEL_VALUE_NUM)
        costs.append(L.classification_cost(input=score, label=label,
                                           name=f"cost_{(i + 1) * 5}min"))
        outputs.append(score)
    return {
        "cost": costs,
        "outputs": outputs,
        "optimizer": optim.RMSProp(learning_rate=1e-3),
        "train_reader": reader_mod.batch(
            reader_mod.shuffle(samples, 4096, seed=0), 128),
        "feeding": feeding,
    }


def main(num_passes=2):
    cfg = get_config()
    trainer = SGD(cost=cfg["cost"], update_equation=cfg["optimizer"], seed=0)
    trainer.train(cfg["train_reader"], num_passes=num_passes,
                  feeding=cfg["feeding"], log_period=20)
    return trainer


if __name__ == "__main__":
    main()
