"""GAN on synthetic 2D data (reference demo/gan/gan_trainer.py:251-265 —
dual GradientMachines driven from Python; here: two Topologies with
alternating jitted update steps, same framework surface).

The generator maps z -> 2D points; the discriminator classifies
real (a ring) vs generated.  Demonstrates multi-network training with
shared step machinery outside SGD.train."""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.layers.graph import Topology, reset_names

Z, H = 8, 32


def build():
    reset_names()
    # generator graph
    z = L.data_layer("z", size=Z)
    g_h = L.fc_layer(z, size=H, act="relu", name="g_h")
    fake = L.fc_layer(g_h, size=2, act=None, name="g_out")
    # discriminator graph (applied to either real or fake points)
    pt = L.data_layer("pt", size=2)
    d_h = L.fc_layer(pt, size=H, act="relu", name="d_h")
    d_out = L.fc_layer(d_h, size=1, act="sigmoid", name="d_out")
    return Topology(fake), Topology(d_out), fake, d_out


def real_batch(rng, n=64):
    theta = rng.uniform(0, 2 * np.pi, n).astype(np.float32)
    r = 1.0 + 0.05 * rng.randn(n).astype(np.float32)
    return np.stack([r * np.cos(theta), r * np.sin(theta)], -1)


def main(steps=400, log_period=100):
    g_topo, d_topo, fake_l, d_l = build()
    key = jax.random.PRNGKey(0)
    kg, kd = jax.random.split(key)
    g_params = g_topo.init(kg)
    d_params = d_topo.init(kd)
    g_opt = optim.Adam(learning_rate=2e-3)
    d_opt = optim.Adam(learning_rate=2e-3)
    g_state, d_state = g_opt.init(g_params), d_opt.init(d_params)
    eps = 1e-6

    def d_score(dp, pts):
        return d_topo.apply(dp, {"pt": pts}, mode="test")

    @jax.jit
    def d_step(dp, ds, gp, z, real):
        def loss(dp):
            fake = g_topo.apply(gp, {"z": z}, mode="test")
            s_real = d_score(dp, real)
            s_fake = d_score(dp, fake)
            return -jnp.mean(jnp.log(s_real + eps)
                             + jnp.log(1 - s_fake + eps))
        l, g = jax.value_and_grad(loss)(dp)
        dp, ds = d_opt.update(g, ds, dp)
        return dp, ds, l

    @jax.jit
    def g_step(gp, gs, dp, z):
        def loss(gp):
            fake = g_topo.apply(gp, {"z": z}, mode="test")
            return -jnp.mean(jnp.log(d_score(dp, fake) + eps))
        l, g = jax.value_and_grad(loss)(gp)
        gp, gs = g_opt.update(g, gs, gp)
        return gp, gs, l

    rng = np.random.RandomState(0)
    for i in range(steps):
        z = jnp.asarray(rng.randn(64, Z), jnp.float32)
        real = jnp.asarray(real_batch(rng))
        d_params, d_state, dl = d_step(d_params, d_state, g_params, z, real)
        z = jnp.asarray(rng.randn(64, Z), jnp.float32)
        g_params, g_state, gl = g_step(g_params, g_state, d_params, z)
        if (i + 1) % log_period == 0:
            print(f"step {i+1}: d_loss={float(dl):.4f} g_loss={float(gl):.4f}")

    # generated points should land near the unit ring
    z = jnp.asarray(rng.randn(256, Z), jnp.float32)
    pts = np.asarray(g_topo.apply(g_params, {"z": z}, mode="test"))
    radii = np.sqrt((pts ** 2).sum(-1))
    print(f"generated radius mean={radii.mean():.3f} (target 1.0) "
          f"std={radii.std():.3f}")
    return radii


if __name__ == "__main__":
    main()
