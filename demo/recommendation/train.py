"""MovieLens recommender demo (reference demo/recommendation api_train_v2) —
functional dual-tower model from paddle_tpu.models.recommendation."""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import pad_sequences
from paddle_tpu.models import recommendation
from paddle_tpu import optim
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.data.datasets import movielens
from paddle_tpu.utils.logging import logger


def feed_batch(batch):
    uid = jnp.asarray([b[0] for b in batch], jnp.int32)
    gender = jnp.asarray([b[1] for b in batch], jnp.int32)
    age = jnp.asarray([b[2] for b in batch], jnp.int32)
    job = jnp.asarray([b[3] for b in batch], jnp.int32)
    mid = jnp.asarray([b[4] for b in batch], jnp.int32)
    cats = np.zeros((len(batch), movielens.CATEGORIES), np.float32)
    for i, b in enumerate(batch):
        cats[i, np.asarray(b[5], np.int64)] = 1.0
    title = pad_sequences([np.asarray(b[6], np.int32) for b in batch])
    score = jnp.asarray([b[7] for b in batch], jnp.float32)
    return (uid, gender, age, job, mid, jnp.asarray(cats), title, score)


def main(num_passes=2, batch_size=128):
    params = recommendation.init(jax.random.PRNGKey(0))
    opt = optim.Adam(learning_rate=1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, *feed):
        loss, grads = jax.value_and_grad(recommendation.loss)(params, *feed)
        params, opt_state = opt.update(grads, opt_state, params)
        return params, opt_state, loss

    reader = reader_mod.batch(
        reader_mod.shuffle(movielens.train(), 1024, seed=0), batch_size)
    for p in range(num_passes):
        losses = []
        for batch in reader():
            params, opt_state, loss = step(params, opt_state,
                                           *feed_batch(batch))
            losses.append(float(loss))
        logger.info("pass %d mean loss %.4f", p, np.mean(losses))
    return params


if __name__ == "__main__":
    main()
