"""Model-zoo feature extraction (reference demo/model_zoo/resnet/classify.py
+ embedding/extract_para.py): load a trained checkpoint, run images through
ResNet and dump an intermediate feature layer, or pull an embedding table
out of a checkpoint into .npz/text.

Usage:
  python extract_features.py resnet  --model_dir DIR --out feats.npz \
      [--layer pool] [--depth 50]
  python extract_features.py embedding --model_dir DIR --param src_emb \
      --out emb.npz [--text emb.txt]

  python extract_features.py import_torch --torch_file resnet50.pth \
      --depth 50 --out_dir model   # torchvision key convention; BN
                                   # running stats land in model_state

With no --model_dir, randomly-initialized weights are used so the demo runs
end-to-end without a download (the reference ships get_model.sh instead).
golden_features.npz pins the import path: features extracted through this
CLI from the deterministic torchvision-convention checkpoint built by
tests/test_model_zoo.py, which also proves them equal to torch's own
forward on the same weights (regenerate by re-running the commands in
test_model_zoo_demo_end_to_end)."""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.utils import logger


def load_params(model_dir, pass_id=None):
    from paddle_tpu.trainer.checkpoint import load_checkpoint
    params, _opt, model_state, _meta = load_checkpoint(model_dir, pass_id)
    return params, model_state


def run_resnet(args):
    from paddle_tpu.models import resnet
    if args.model_dir:
        # model_state carries the BN running stats — required in test mode
        params, state = load_params(args.model_dir, args.pass_id)
    else:
        logger.info("no --model_dir: using random init")
        params, state = resnet.init(jax.random.PRNGKey(0), depth=args.depth)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.rand(args.batch, 224, 224, 3), jnp.float32) \
        if not args.images else jnp.asarray(np.load(args.images))

    if args.layer == "pool":
        feats = resnet.features(params, state, images, depth=args.depth)
    else:
        feats, _ = resnet.forward(params, state, images, depth=args.depth,
                                  train=False)
    np.savez(args.out, features=np.asarray(feats))
    logger.info("wrote %s: %s", args.out, np.asarray(feats).shape)


def _save_embedding(table, out_path, text_path):
    """npz + reference extract_para.py text format (one row per word) —
    shared by the checkpoint and pretrained-binary subcommands."""
    np.savez(out_path, embedding=table)
    logger.info("wrote %s: vocab=%d dim=%d", out_path, *table.shape)
    if text_path:
        with open(text_path, "w") as f:
            f.write(f"{table.shape[0]} {table.shape[1]}\n")
            for row in table:
                f.write(" ".join(f"{v:.6f}" for v in row) + "\n")
        logger.info("wrote %s", text_path)


def run_embedding(args):
    params, _ = load_params(args.model_dir, args.pass_id)
    node = params
    for part in args.param.split("/"):
        node = node[part]
    table = np.asarray(node["w"] if isinstance(node, dict) and "w" in node
                       else node)
    _save_embedding(table, args.out, args.text)


def run_import_torch(args):
    """Convert a torch checkpoint (torchvision ResNet key convention) into
    a paddle_tpu pass dir — the reference's get_model.sh role: after this,
    `resnet --model_dir` extracts features from the PRETRAINED weights
    (reference demo/model_zoo/resnet/classify.py on a downloaded model)."""
    import torch
    from paddle_tpu.trainer.checkpoint import save_checkpoint
    from paddle_tpu.utils.tools.torch_import import import_torchvision_resnet
    sd = torch.load(args.torch_file, map_location="cpu", weights_only=True)
    if isinstance(sd, dict) and "conv1.weight" not in sd:
        sd = sd.get("state_dict", sd)   # wrapped checkpoints
    if not isinstance(sd, dict) or "conv1.weight" not in sd:
        raise SystemExit(
            f"{args.torch_file}: expected a state_dict in torchvision "
            "ResNet naming (conv1.weight, layer1.0..., fc.weight), got "
            f"{type(sd).__name__}")
    params, state = import_torchvision_resnet(sd, depth=args.depth)
    save_checkpoint(args.out_dir, 0, params, model_state=state,
                    extra={"imported_from": os.path.basename(args.torch_file),
                           "depth": args.depth})
    logger.info("imported %s (depth %d) -> %s/pass-00000",
                args.torch_file, args.depth, args.out_dir)


def run_ref_embedding(args):
    """Reference demo/model_zoo/embedding workflow (extract_para.py): pull
    a sub-dict's rows out of a PRETRAINED reference-format binary
    embedding table and write npz (+ the reference text format)."""
    from paddle_tpu.utils.tools import ref_params
    indices = (np.loadtxt(args.indices, dtype=np.int64, ndmin=1)
               if args.indices else None)       # None = every row, one read
    rows = ref_params.extract_rows(args.emb_file, indices, args.dim)
    _save_embedding(rows, args.out, args.text)


def main(argv=None):
    p = argparse.ArgumentParser()
    sub = p.add_subparsers(dest="what", required=True)
    r = sub.add_parser("resnet")
    r.add_argument("--model_dir", default=None)
    r.add_argument("--pass_id", type=int, default=None)
    r.add_argument("--depth", type=int, default=50)
    r.add_argument("--layer", default="logits", choices=["logits", "pool"])
    r.add_argument("--images", default=None,
                   help=".npy of [N,224,224,3] floats")
    r.add_argument("--batch", type=int, default=2)
    r.add_argument("--out", default="features.npz")
    e = sub.add_parser("embedding")
    e.add_argument("--model_dir", required=True)
    e.add_argument("--pass_id", type=int, default=None)
    e.add_argument("--param", required=True,
                   help="params path to the table, e.g. src_emb or emb/w")
    e.add_argument("--out", default="embedding.npz")
    e.add_argument("--text", default=None)
    t = sub.add_parser("import_torch")
    t.add_argument("--torch_file", required=True,
                   help=".pt/.pth state_dict in torchvision ResNet naming")
    t.add_argument("--depth", type=int, default=50)
    t.add_argument("--out_dir", required=True)
    re_ = sub.add_parser("ref_embedding")
    re_.add_argument("--emb_file", required=True,
                     help="reference-format binary embedding table")
    re_.add_argument("--dim", type=int, required=True)
    re_.add_argument("--indices", default=None,
                     help="file of word ids (one per line); default: all")
    re_.add_argument("--out", default="embedding.npz")
    re_.add_argument("--text", default=None)
    args = p.parse_args(argv)
    if args.what == "resnet":
        run_resnet(args)
    elif args.what == "import_torch":
        run_import_torch(args)
    elif args.what == "ref_embedding":
        run_ref_embedding(args)
    else:
        run_embedding(args)


if __name__ == "__main__":
    main()
