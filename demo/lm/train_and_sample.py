"""Decoder-only language-model demo (post-reference capability:
models/transformer.lm_loss + lm_generate).

A char-level LM learns a tiny synthetic grammar (zero egress), trained
PADDING-FREE — ragged sentences first-fit-packed into full rows by the
`packed` reader decorator, attention block-diagonal per segment — then
samples continuations through the KV-cached generator.  The same loss
scales to a data x seq mesh with zigzag ring attention
(lm_loss(mesh=..., zigzag=True)); see docs/cluster_training.md.

Run:  python demo/lm/train_and_sample.py [--epochs 12]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

# the grammar: subject verb object ".", tokenized per char group
WORDS = {
    "sub": ["cat", "dog", "bird"],
    "verb": ["sees", "likes"],
    "obj": ["fish", "seed", "bone"],
}
CHARS = sorted({c for ws in WORDS.values() for w in ws for c in w}
               | {" ", "."})
PAD, BOS = 0, 1
VOCAB = len(CHARS) + 2
ENC = {c: i + 2 for i, c in enumerate(CHARS)}
DEC = {i: c for c, i in ENC.items()}


def sentences(n, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        s = " ".join([rng.choice(WORDS["sub"]), rng.choice(WORDS["verb"]),
                      rng.choice(WORDS["obj"])]) + "."
        yield np.asarray([BOS] + [ENC[c] for c in s], np.int32)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--max_len", type=int, default=24)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    # a sitecustomize hook may have pinned the jax_platforms CONFIG at
    # interpreter startup (routing at a remote TPU); the env var alone
    # does not override it — honor JAX_PLATFORMS explicitly
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.data import reader as reader_mod
    from paddle_tpu.models import transformer
    from paddle_tpu import optim

    params = transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                              trg_vocab=1, d_model=48, dff=96,
                              enc_layers=2, dec_layers=0,
                              max_len=args.max_len)
    opt = optim.Adam(learning_rate=3e-3)
    state = opt.init(params)
    packed = reader_mod.batch(
        reader_mod.packed(lambda: sentences(512), args.max_len,
                          buffer_size=64), args.batch, drop_last=True)

    @jax.jit
    def step(p, s, data, seg, pos):
        toks = SequenceBatch(data, jnp.full((data.shape[0],),
                                            args.max_len, jnp.int32))
        l, g = jax.value_and_grad(lambda p: transformer.lm_loss(
            p, toks, 4, segment_ids=seg, positions=pos))(p)
        p2, s2 = opt.update(g, s, p)
        return p2, s2, l

    loss = None
    for epoch in range(args.epochs):
        for rows in packed():
            params, state, loss = step(
                params, state,
                jnp.asarray(np.stack([r[0] for r in rows])),
                jnp.asarray(np.stack([r[1] for r in rows])),
                jnp.asarray(np.stack([r[2] for r in rows])))
        print(f"epoch {epoch}: loss {float(loss):.4f}", flush=True)

    # sample continuations from subject prompts (greedy + temperature)
    for prompt_txt in ("cat ", "bird "):
        prompt = np.asarray([[BOS] + [ENC[c] for c in prompt_txt]],
                            np.int32)
        ids = np.asarray(transformer.lm_generate(
            params, prompt, max_len=args.max_len, num_heads=4))[0]
        txt = "".join(DEC.get(int(i), "") for i in ids[1:])
        print(f"greedy   {prompt_txt!r} -> {txt!r}", flush=True)
        ids = np.asarray(transformer.lm_generate(
            params, prompt, max_len=args.max_len, num_heads=4,
            temperature=0.7, top_k=8, rng=jax.random.PRNGKey(7)))[0]
        txt = "".join(DEC.get(int(i), "") for i in ids[1:])
        print(f"sampled  {prompt_txt!r} -> {txt!r}", flush=True)
    return float(loss)


if __name__ == "__main__":
    main()
