"""Pipeline-parallelism demo (post-reference capability;
parallel/pipeline.py).  A stack of identical residual-MLP blocks learns a
1-D regression, trained through the GPipe schedule: each mesh 'stage'
device owns one block, microbatches tick through the schedule, and the
backward pass is jax.grad straight through the ppermute rotation.

The reference's nearest ancestor is ParallelNeuralNetwork's `device=N`
layer placement (ParallelNeuralNetwork.cpp:15-60).  Run on any device
count — the mesh shape adapts; on one device the schedule still runs
(S=1, a plain loop), which is how this demo doubles as a CPU smoke test:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python demo/pipeline/train.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.parallel import (MeshConfig, make_mesh, gpipe,
                                 stack_stages, stage_spec, microbatch,
                                 unmicrobatch)

D_HIDDEN = 32
MICRO = 4


def stage_fn(p, h):
    """One pipeline stage: residual MLP block, shape-preserving."""
    return h + jnp.tanh(h @ p["w1"] + p["b1"]) @ p["w2"]


def main():
    n = len(jax.devices())
    stages = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    mesh = make_mesh(MeshConfig(data=n // stages, stage=stages))
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    rng = np.random.RandomState(0)
    stacked = stack_stages([
        {"w1": jnp.asarray(rng.randn(D_HIDDEN, D_HIDDEN) * 0.2, jnp.float32),
         "b1": jnp.zeros((D_HIDDEN,), jnp.float32),
         "w2": jnp.asarray(rng.randn(D_HIDDEN, D_HIDDEN) * 0.2, jnp.float32)}
        for _ in range(stages)])

    # task: y = sin(3x) embedded in a D_HIDDEN-wide space
    xs = rng.uniform(-1, 1, (512, 1)).astype(np.float32)
    enc = np.tile(xs, (1, D_HIDDEN)).astype(np.float32)
    ys = np.sin(3 * xs).astype(np.float32)
    x_all = jnp.asarray(enc)
    y_all = jnp.asarray(ys)

    from jax.sharding import NamedSharding, PartitionSpec as P
    psh = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P("stage")), stacked)
    stacked = jax.device_put(stacked, psh)

    # the shallow 1-stage model takes (and tolerates) a hotter step
    lr = 0.05 if stages > 1 else 0.3

    @jax.jit
    def step(sp, x, y):
        def loss_fn(sp):
            out = unmicrobatch(gpipe(stage_fn, sp, microbatch(x, MICRO),
                                     mesh=mesh, data_axis="data"))
            pred = out.mean(axis=1, keepdims=True)
            return jnp.mean((pred - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(sp)
        return jax.tree_util.tree_map(
            lambda w, gw: w - lr * gw, sp, g), loss

    # a 1-device mesh means a 1-block model (stage count = mesh size),
    # which needs more steps to hit the same relative-improvement bar
    epochs = 60 if stages > 1 else 400
    first = None
    for epoch in range(epochs):
        sp_loss = step(stacked, x_all, y_all)
        stacked, loss = sp_loss
        if first is None:
            first = float(loss)
        if (epoch + 1) % (epochs // 3) == 0:
            print(f"epoch {epoch + 1}: loss {float(loss):.5f}")
    final = float(loss)
    print(f"loss {first:.4f} -> {final:.4f} "
          f"({'OK' if final < 0.5 * first else 'NO IMPROVEMENT'})")
    assert final < 0.5 * first


if __name__ == "__main__":
    main()
