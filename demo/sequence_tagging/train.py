"""Linear-CRF sequence tagging (reference demo/sequence_tagging linear_crf
NER config): context-window features -> fc -> CRF loss + decoding."""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import integer_value_sequence
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.data.datasets import conll05


def get_config():
    num_labels = conll05.NUM_LABELS
    words = L.data_layer("words", size=conll05.WORD_DICT, is_seq=True)
    preds = L.data_layer("preds", size=conll05.PRED_DICT, is_seq=True)
    label = L.data_layer("label", size=1, is_seq=True)

    word_emb = L.embedding_layer(words, size=64)
    pred_emb = L.embedding_layer(preds, size=32)
    feats = L.mixed_layer(size=64 * 3 + 32, input=[
        L.context_projection(word_emb, context_len=3, context_start=-1),
        L.identity_projection(pred_emb),
    ], act=None)
    hidden = L.fc_layer(feats, size=128, act="tanh")
    emission = L.fc_layer(hidden, size=num_labels, act=None)
    crf_cost = L.crf_layer(emission, label, size=num_labels, name="crf")
    decoded = L.crf_decoding_layer(emission, size=num_labels,
                                   param_name=crf_cost.cfg["param_name"])
    return {
        "cost": crf_cost,
        "output": decoded,
        "optimizer": optim.Momentum(learning_rate=0.01, momentum=0.9,
                                    l2=1e-4),
        "train_reader": reader_mod.batch(
            reader_mod.shuffle(conll05.train(), 256, seed=0), 32),
        "test_reader": reader_mod.batch(conll05.test(), 32),
        "feeding": {"words": integer_value_sequence(conll05.WORD_DICT),
                    "preds": integer_value_sequence(conll05.PRED_DICT),
                    "label": integer_value_sequence(num_labels)},
    }
