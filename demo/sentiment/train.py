"""IMDB sentiment stacked bi-LSTM (reference demo/sentiment
sentiment_net.py stacked_lstm_net / bidirectional_lstm_net)."""

import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import integer_value_sequence, integer_value
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.data.datasets import imdb

DICT_DIM = imdb.WORD_DIM


def stacked_lstm_net(words, label, hid=128, stacked_num=3):
    emb = L.embedding_layer(words, size=128)
    fc1 = L.fc_layer(emb, size=hid, act=None)
    lstm1 = L.lstmemory(L.fc_layer(fc1, size=hid * 4, act=None,
                                   bias_attr=False), size=hid)
    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = L.fc_layer(L.concat_layer(inputs), size=hid, act=None)
        lstm = L.lstmemory(L.fc_layer(fc, size=hid * 4, act=None,
                                      bias_attr=False),
                           size=hid, reverse=(i % 2 == 0))
        inputs = [fc, lstm]
    fc_last = L.pooling_layer(inputs[0], pooling_type=L.pooling.Max)
    lstm_last = L.pooling_layer(inputs[1], pooling_type=L.pooling.Max)
    out = L.fc_layer(L.concat_layer([fc_last, lstm_last]), size=2,
                     act="softmax")
    return L.classification_cost(out, label), out


def get_config():
    words = L.data_layer("words", size=DICT_DIM, is_seq=True)
    label = L.data_layer("label", size=1)
    cost, out = stacked_lstm_net(words, label)
    return {
        "cost": cost,
        "output": out,
        "optimizer": optim.Adam(learning_rate=0.002, l2=1e-4,
                                clip_norm=5.0),
        "train_reader": reader_mod.batch(
            reader_mod.shuffle(imdb.train(), 512, seed=0), 64),
        "test_reader": reader_mod.batch(imdb.test(), 64),
        "feeding": {"words": integer_value_sequence(DICT_DIM),
                    "label": integer_value(2)},
    }
